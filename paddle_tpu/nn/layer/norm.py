"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ..layer_base import Layer
from ..initializer import Constant
from .. import functional as F
from ...core import dtype as dtypes


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        import jax.numpy as jnp
        from ...core.tensor import Tensor
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/GSPMD the batch axis is sharded and XLA computes
    global statistics automatically when the reduction spans the data axis — so
    SyncBatchNorm == BatchNorm in the compiled path (reference needed an explicit
    NCCL allreduce: python/paddle/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True,
                default_initializer=Constant(0.0))

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-first extra (the reference ships it as incubate fused_rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups, self._num_channels = num_groups, num_channels
        self._epsilon, self._data_format = epsilon, data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias,
                            self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale, self.bias = None, None
        else:
            self.scale = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True,
                default_initializer=Constant(0.0))

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Normalizes an input weight tensor by its largest singular value via
    power iteration (reference: nn/layer/norm.py SpectralNorm — the layer form
    that takes the weight as forward input; the wrapper form is
    nn.utils.spectral_norm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        import numpy as np
        self.dim = dim
        self.power_iters = power_iters
        self.eps = epsilon
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=None)
        self.weight_v = self.create_parameter(
            [w], default_initializer=None)
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp
        from ...core.tensor import dispatch
        dim, iters, eps = self.dim, self.power_iters, self.eps

        def fn(w, u, v):
            import jax as _jax
            perm = [dim] + [i for i in range(w.ndim) if i != dim]
            mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
            # power iteration runs under stop_gradient: u/v are constants in
            # the backward pass, matching the reference (only sigma = uᵀWv is
            # differentiated)
            mat_ng = _jax.lax.stop_gradient(mat)
            for _ in range(iters):
                v = mat_ng.T @ u
                v = v / jnp.maximum(jnp.linalg.norm(v), eps)
                u = mat_ng @ v
                u = u / jnp.maximum(jnp.linalg.norm(u), eps)
            u = _jax.lax.stop_gradient(u)
            v = _jax.lax.stop_gradient(v)
            sigma = u @ mat @ v
            return w / sigma, u, v

        out, new_u, new_v = dispatch(
            fn, (weight, self.weight_u, self.weight_v), {},
            name="spectral_norm")
        self.weight_u._value = new_u._value
        self.weight_v._value = new_v._value
        return out
