"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import math

from ..layer_base import Layer, ParamAttr
from ..initializer import XavierNormal, Uniform, Normal, Constant
from .. import functional as F
from ...core import dtype as dtypes


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, weight [in, out] (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        k = 1.0 / math.sqrt(in_features)
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierNormal())
        self.bias = self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ... import ops
        return ops.flatten(x, self.start_axis, self.stop_axis)


class Embedding(Layer):
    """reference: nn/layer/common.py Embedding; weight [num_embeddings, dim]."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (padding_idx if padding_idx is None or padding_idx >= 0
                             else num_embeddings + padding_idx)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierNormal())
        if self._padding_idx is not None:
            import jax.numpy as jnp
            self.weight._value = self.weight._value.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.r, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.r, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr,
            default_initializer=XavierNormal(fan_in=in1_features,
                                             fan_out=out_features))
        self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = \
            padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = \
            padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = \
            padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.args = (p, epsilon, keepdim)

    def forward(self, x, y):
        p, e, k = self.args
        return F.pairwise_distance(x, y, p, e, k)


class Unflatten(Layer):
    """Reference: nn/layer/common.py Unflatten — expand one axis to a shape."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, list(shape)

    def forward(self, x):
        from ... import ops
        new_shape = list(x.shape)
        axis = self.axis % len(new_shape)
        new_shape[axis:axis + 1] = self.shape
        return ops.reshape(x, new_shape)


class FeatureAlphaDropout(Layer):
    """Alpha dropout zeroing whole channels (reference: nn/layer/common.py)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


class ZeroPad1D(Layer):
    """reference: nn/layer/common.py ZeroPad1D — constant-0 pad on the L dim."""

    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self._pad = padding if not isinstance(padding, int) \
            else [padding, padding]
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, mode="constant", value=0.0,
                     data_format=self._data_format)


class ZeroPad3D(Layer):
    """reference: nn/layer/common.py ZeroPad3D."""

    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self._pad = padding if not isinstance(padding, int) \
            else [padding] * 6
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, mode="constant", value=0.0,
                     data_format=self._data_format)
