"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from ..layer_base import Layer
from .. import functional as F


def _pool_layer(name, fn_name, arg_names):
    fn = getattr(F, fn_name)

    class _Pool(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(zip(arg_names, args))
            merged.update(kwargs)
            merged.pop("name", None)
            self._kwargs = merged

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Pool.__name__ = name
    _Pool.__qualname__ = name
    return _Pool


_MAX_ARGS = ["kernel_size", "stride", "padding", "return_mask", "ceil_mode",
             "data_format"]
_AVG1_ARGS = ["kernel_size", "stride", "padding", "exclusive", "ceil_mode",
              "data_format"]
_AVG_ARGS = ["kernel_size", "stride", "padding", "ceil_mode", "exclusive",
             "divisor_override", "data_format"]

MaxPool1D = _pool_layer("MaxPool1D", "max_pool1d", _MAX_ARGS)
MaxPool2D = _pool_layer("MaxPool2D", "max_pool2d", _MAX_ARGS)
MaxPool3D = _pool_layer("MaxPool3D", "max_pool3d", _MAX_ARGS)
AvgPool1D = _pool_layer("AvgPool1D", "avg_pool1d", _AVG1_ARGS)
AvgPool2D = _pool_layer("AvgPool2D", "avg_pool2d", _AVG_ARGS)
AvgPool3D = _pool_layer("AvgPool3D", "avg_pool3d", _AVG_ARGS)
AdaptiveAvgPool1D = _pool_layer("AdaptiveAvgPool1D", "adaptive_avg_pool1d",
                                ["output_size"])
AdaptiveAvgPool2D = _pool_layer("AdaptiveAvgPool2D", "adaptive_avg_pool2d",
                                ["output_size", "data_format"])
AdaptiveAvgPool3D = _pool_layer("AdaptiveAvgPool3D", "adaptive_avg_pool3d",
                                ["output_size", "data_format"])
AdaptiveMaxPool1D = _pool_layer("AdaptiveMaxPool1D", "adaptive_max_pool1d",
                                ["output_size", "return_mask"])
AdaptiveMaxPool2D = _pool_layer("AdaptiveMaxPool2D", "adaptive_max_pool2d",
                                ["output_size", "return_mask"])
AdaptiveMaxPool3D = _pool_layer("AdaptiveMaxPool3D", "adaptive_max_pool3d",
                                ["output_size", "return_mask"])
