"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from ..layer_base import Layer
from .. import functional as F


def _pool_layer(name, fn_name, arg_names):
    fn = getattr(F, fn_name)

    class _Pool(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(zip(arg_names, args))
            merged.update(kwargs)
            merged.pop("name", None)
            self._kwargs = merged

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Pool.__name__ = name
    _Pool.__qualname__ = name
    return _Pool


_MAX_ARGS = ["kernel_size", "stride", "padding", "return_mask", "ceil_mode",
             "data_format"]
_AVG1_ARGS = ["kernel_size", "stride", "padding", "exclusive", "ceil_mode",
              "data_format"]
_AVG_ARGS = ["kernel_size", "stride", "padding", "ceil_mode", "exclusive",
             "divisor_override", "data_format"]

MaxPool1D = _pool_layer("MaxPool1D", "max_pool1d", _MAX_ARGS)
MaxPool2D = _pool_layer("MaxPool2D", "max_pool2d", _MAX_ARGS)
MaxPool3D = _pool_layer("MaxPool3D", "max_pool3d", _MAX_ARGS)
AvgPool1D = _pool_layer("AvgPool1D", "avg_pool1d", _AVG1_ARGS)
AvgPool2D = _pool_layer("AvgPool2D", "avg_pool2d", _AVG_ARGS)
AvgPool3D = _pool_layer("AvgPool3D", "avg_pool3d", _AVG_ARGS)
AdaptiveAvgPool1D = _pool_layer("AdaptiveAvgPool1D", "adaptive_avg_pool1d",
                                ["output_size"])
AdaptiveAvgPool2D = _pool_layer("AdaptiveAvgPool2D", "adaptive_avg_pool2d",
                                ["output_size", "data_format"])
AdaptiveAvgPool3D = _pool_layer("AdaptiveAvgPool3D", "adaptive_avg_pool3d",
                                ["output_size", "data_format"])
AdaptiveMaxPool1D = _pool_layer("AdaptiveMaxPool1D", "adaptive_max_pool1d",
                                ["output_size", "return_mask"])
AdaptiveMaxPool2D = _pool_layer("AdaptiveMaxPool2D", "adaptive_max_pool2d",
                                ["output_size", "return_mask"])
AdaptiveMaxPool3D = _pool_layer("AdaptiveMaxPool3D", "adaptive_max_pool3d",
                                ["output_size", "return_mask"])

LPPool1D = _pool_layer("LPPool1D", "lp_pool1d",
                       ["norm_type", "kernel_size", "stride", "padding",
                        "ceil_mode", "data_format"])
LPPool2D = _pool_layer("LPPool2D", "lp_pool2d",
                       ["norm_type", "kernel_size", "stride", "padding",
                        "ceil_mode", "data_format"])
FractionalMaxPool2D = _pool_layer(
    "FractionalMaxPool2D", "fractional_max_pool2d",
    ["output_size", "kernel_size", "random_u", "return_mask"])
FractionalMaxPool3D = _pool_layer(
    "FractionalMaxPool3D", "fractional_max_pool3d",
    ["output_size", "kernel_size", "random_u", "return_mask"])


def _unpool_layer(name, fn_name):
    fn = getattr(F, fn_name)

    class _Unpool(Layer):
        def __init__(self, kernel_size, stride=None, padding=0,
                     data_format=None, output_size=None, name=None):
            super().__init__()
            self._args = dict(kernel_size=kernel_size, stride=stride,
                              padding=padding, output_size=output_size)
            if data_format is not None:
                self._args["data_format"] = data_format

        def forward(self, x, indices):
            return fn(x, indices, **self._args)

    _Unpool.__name__ = name
    _Unpool.__qualname__ = name
    return _Unpool


MaxUnPool1D = _unpool_layer("MaxUnPool1D", "max_unpool1d")
MaxUnPool2D = _unpool_layer("MaxUnPool2D", "max_unpool2d")
MaxUnPool3D = _unpool_layer("MaxUnPool3D", "max_unpool3d")
