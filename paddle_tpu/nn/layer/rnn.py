"""RNN layers (reference: python/paddle/nn/layer/rnn.py → phi rnn kernels/cuDNN).

TPU-native: cells are pure step functions; the sequence loop is lax.scan, which XLA
compiles into a single fused loop (no per-step dispatch). Multi-layer and
bidirectional stacks compose scans.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..layer_base import Layer
from ..initializer import Uniform
from ...core.tensor import Tensor, dispatch
from ... import ops


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0,
                           batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        return ops.full([b, self.hidden_size], init_value,
                        dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((hidden_size,), bias_ih_attr, is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter((hidden_size,), bias_hh_attr, is_bias=True,
                                             default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = dispatch(fn, (inputs, states, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh), {}, name="simple_rnn_cell")
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((4 * hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((4 * hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            b = inputs.shape[0]
            states = (ops.zeros([b, self.hidden_size]), ops.zeros([b, self.hidden_size]))
        h, c = states

        def fn(x, hp, cp, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hp @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            cn = f * cp + i * g
            hn = o * jnp.tanh(cn)
            return hn, cn
        hn, cn = dispatch(fn, (inputs, h, c, self.weight_ih, self.weight_hh,
                               self.bias_ih, self.bias_hh), {}, name="lstm_cell")
        return hn, (hn, cn)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((3 * hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((3 * hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, hp, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = hp @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * hp
        h = dispatch(fn, (inputs, states, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh), {}, name="gru_cell")
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Wraps a cell into a sequence op (reference: nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # eager python loop over time (correctness path; the jit path fuses via scan
        # because the whole loop is traced into one program)
        x = inputs
        if not self.time_major:
            x = ops.transpose(x, [1, 0, 2])
        T = x.shape[0]
        states = initial_states
        outs = []
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in steps:
            out, states = self.cell(x[t], states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        y = ops.stack(outs, axis=0)
        if not self.time_major:
            y = ops.transpose(y, [1, 0, 2])
        return y, states


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **cell_kwargs):
        super().__init__()
        self.mode = mode
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        cell_cls = {"RNN_TANH": SimpleRNNCell, "RNN_RELU": SimpleRNNCell,
                    "LSTM": LSTMCell, "GRU": GRUCell}[mode]
        extra = {}
        if mode == "RNN_TANH":
            extra["activation"] = "tanh"
        elif mode == "RNN_RELU":
            extra["activation"] = "relu"
        from .containers import LayerList
        self.rnns = LayerList()
        for layer in range(num_layers):
            for d in range(num_dir):
                in_sz = input_size if layer == 0 else hidden_size * num_dir
                cell = cell_cls(in_sz, hidden_size, **extra)
                self.rnns.append(RNN(cell, is_reverse=(d == 1),
                                     time_major=time_major))
        self.hidden_size = hidden_size

    def forward(self, inputs, initial_states=None, sequence_length=None):
        num_dir = 2 if self.bidirectional else 1
        x = inputs
        final_states = []
        idx = 0
        from .. import functional as F
        for layer in range(self.num_layers):
            outs = []
            for d in range(num_dir):
                y, st = self.rnns[idx](x, None if initial_states is None else None)
                outs.append(y)
                final_states.append(st)
                idx += 1
            x = outs[0] if num_dir == 1 else ops.concat(outs, axis=-1)
            if self.dropout > 0 and layer < self.num_layers - 1:
                x = F.dropout(x, self.dropout, training=self.training)
        if self.mode == "LSTM":
            h = ops.stack([s[0] for s in final_states], axis=0)
            c = ops.stack([s[1] for s in final_states], axis=0)
            return x, (h, c)
        h = ops.stack(final_states, axis=0)
        return x, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        yf, sf = self.rnn_fw(inputs, None)
        yb, sb = self.rnn_bw(inputs, None)
        return ops.concat([yf, yb], axis=-1), (sf, sb)
