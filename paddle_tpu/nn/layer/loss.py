"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from ..layer_base import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                 name=None):
        super().__init__()
        self.weight = weight
        self.args = dict(ignore_index=ignore_index, reduction=reduction,
                         soft_label=soft_label, axis=axis, use_softmax=use_softmax,
                         label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight, **self.args)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight,
                                                  self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self.delta, self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction, self.log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        m, p, e, s, r = self.args
        return F.triplet_margin_loss(input, positive, negative, m, p, e, s, r)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        li, fl, e, r = self.args
        return F.poisson_nll_loss(input, label, li, fl, e, r)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        fl, e, r = self.args
        return F.gaussian_nll_loss(input, label, variance, fl, e, r)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        d, m, s, r = self.args
        return F.triplet_margin_with_distance_loss(input, positive, negative,
                                                   d, m, s, r)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (reference: nn/layer/loss.py HSigmoidLoss):
    complete-binary-tree hierarchical softmax; weight [num_classes-1, F],
    bias [num_classes-1, 1]."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if (num_classes < 2) and (not is_custom):
            raise ValueError(
                "num_classes must not be less than 2 with default tree")
        self._feature_size = feature_size
        self._num_classes = num_classes
        self._is_custom = is_custom
        C = num_classes if is_custom else num_classes - 1
        self.weight = self.create_parameter([C, feature_size], weight_attr)
        self.bias = self.create_parameter([C, 1], bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table, path_code)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank, self.fastemit_lambda = blank, fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax head (reference: nn/layer/loss.py
    AdaptiveLogSoftmaxWithLoss): shortlist head + projected tail clusters with
    div_value^i shrinking projections."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if (cutoffs != sorted(cutoffs) or min(cutoffs) <= 0
                or max(cutoffs) > (n_classes - 1)
                or len(set(cutoffs)) != len(cutoffs)
                or any(int(c) != c for c in cutoffs)):
            raise ValueError(
                "cutoffs should be a sequence of unique, positive integers "
                "sorted in an increasing order, where each value is between "
                "1 and n_classes-1")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.shortlist_size = self.cutoffs[0]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.shortlist_size + self.n_clusters
        self.head_weight = self.create_parameter(
            [in_features, self.head_size], weight_attr)
        self.head_bias = self.create_parameter(
            [self.head_size], bias_attr, is_bias=True) if head_bias else None
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = int(in_features // (div_value ** (i + 1)))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter([in_features, hsz], weight_attr)
            cls_w = self.create_parameter([hsz, osz], weight_attr)
            self.add_parameter(f"tail_proj_{i}", proj)
            self.add_parameter(f"tail_cls_{i}", cls_w)
            self.tail_weights.append([proj, cls_w])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, self.head_bias)

    def _full_log_prob(self, input):
        import paddle_tpu as _paddle
        head = input @ self.head_weight
        if self.head_bias is not None:
            head = head + self.head_bias
        head_lp = F.log_softmax(head, axis=-1)
        parts = [head_lp[:, : self.shortlist_size]]
        for i, (proj, cls_w) in enumerate(self.tail_weights):
            tail_lp = F.log_softmax((input @ proj) @ cls_w, axis=-1)
            parts.append(tail_lp + head_lp[:, self.shortlist_size + i
                                           : self.shortlist_size + i + 1])
        return _paddle.concat(parts, axis=-1)

    def log_prob(self, input):
        return self._full_log_prob(input)

    def predict(self, input):
        return self._full_log_prob(input).argmax(axis=-1)
