"""Convolution functionals.

Reference: python/paddle/nn/functional/conv.py → phi conv kernels → cuDNN.
TPU-native: one lowering to lax.conv_general_dilated, which XLA maps onto the MXU
(convs are reshaped into large matmuls by the compiler). Paddle weight layout
[out_c, in_c/groups, *k] is kept so state_dicts match the reference.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import dispatch


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _padding(padding, n):
    """Returns lax padding config: 'SAME'/'VALID' or [(lo,hi)]*n."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # paddle also allows [[0,0],[0,0],[lo,hi],...] including batch/channel dims
    if len(padding) == n + 2:
        return [tuple(p) for p in padding[2:]]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, channel_last):
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    pad = _padding(padding, n)
    if channel_last:
        lhs_spec = "N" + "DHW"[3 - n:] + "C"
    else:
        lhs_spec = "NC" + "DHW"[3 - n:]
    out_spec = lhs_spec
    rhs_spec = "OI" + "DHW"[3 - n:]

    def fn(v, w, *rest):
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad, rhs_dilation=dil,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec),
            feature_group_count=int(groups),
            preferred_element_type=None)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[lhs_spec.index("C")] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    args = (x, weight) + ((bias,) if bias is not None else ())
    return dispatch(fn, args, {}, name=f"conv{n}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format.endswith("C"))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format.endswith("C") and data_format != "NCHW")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format.endswith("C") and data_format != "NCDHW")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, channel_last, output_size=None):
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    opad = _tuple(output_padding, n)
    if channel_last:
        lhs_spec = "N" + "DHW"[3 - n:] + "C"
    else:
        lhs_spec = "NC" + "DHW"[3 - n:]
    rhs_spec = "IO" + "DHW"[3 - n:]  # paddle transpose-conv weight: [in_c, out_c/g, *k]

    def fn(v, w, *rest):
        # Transposed conv == gradient of conv w.r.t. its input: dilate the input by
        # `stride` (lhs_dilation), pad by k_eff-1-p, correlate with the spatially
        # flipped kernel. Paddle's weight layout [in_c, out_c/g, *k] already has the
        # channel transpose, so rhs_spec "IO" + spatial flip completes it.
        k_spatial = w.shape[2:]
        if isinstance(padding, str):
            if padding.upper() == "VALID":
                pad_base = [(0, 0)] * n
            else:  # SAME: output spatial = input * stride
                pad_base = []
                for i in range(n):
                    k_eff = (k_spatial[i] - 1) * dil[i] + 1
                    total = k_eff - strides[i]
                    pad_base.append((total // 2, total - total // 2))
        else:
            pad_base = _padding(padding, n)
        pads = []
        for i in range(n):
            k_eff = (k_spatial[i] - 1) * dil[i] + 1
            lo, hi = pad_base[i]
            pads.append((k_eff - 1 - lo, k_eff - 1 - hi + opad[i]))
        out = jax.lax.conv_general_dilated(
            v, jnp.flip(w, axis=tuple(range(2, 2 + n))),
            window_strides=(1,) * n, padding=pads, lhs_dilation=strides,
            rhs_dilation=dil,
            dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
            feature_group_count=1)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[lhs_spec.index("C")] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    def fn_grouped(v, w, *rest):
        # lax conv_transpose has no groups; emulate by splitting
        if groups == 1:
            return fn(v, w, *rest)
        c_axis = lhs_spec.index("C")
        vs = jnp.split(v, groups, axis=c_axis)
        ws = jnp.split(w, groups, axis=0)
        outs = [fn(vv, ww) for vv, ww in zip(vs, ws)]
        out = jnp.concatenate(outs, axis=c_axis)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[c_axis] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return dispatch(fn_grouped, args, {}, name=f"conv{n}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 1, data_format.endswith("C"), output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 2, data_format == "NHWC", output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                           groups, 3, data_format == "NDHWC", output_size)
