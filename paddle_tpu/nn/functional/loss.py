"""Loss functionals.

Reference: python/paddle/nn/functional/loss.py → phi cross_entropy/bce/... kernels.
cross_entropy fuses log_softmax+gather the way the reference's
softmax_with_cross_entropy kernel does (one pass, no [N, C] probability
materialization in the backward).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch


def _reduce(out, reduction, weight_sum=None):
    if reduction == "mean":
        if weight_sum is not None:
            return jnp.sum(out) / weight_sum
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """Fused softmax+CE (reference: phi softmax_with_cross_entropy kernel)."""
    def fn(logits, lbl, *w):
        ax = int(axis) % logits.ndim
        n_classes = logits.shape[ax]
        # the generic branches compute their log-probs in fp32 (the AMP
        # black-list no longer upcasts cross_entropy — the fused fast path
        # below owns its fp32 accumulation, these own theirs)
        if soft_label:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax) \
                if use_softmax \
                else jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
            loss = -jnp.sum(lbl * logp, axis=ax).astype(logits.dtype)
            if w:
                loss = loss * w[0]
            return _reduce(loss, reduction)
        lbl_int = lbl.astype(jnp.int32)
        if lbl_int.ndim == logits.ndim:
            lbl_int = jnp.squeeze(lbl_int, axis=ax)
        if (use_softmax and label_smoothing == 0.0 and not w
                and ax == logits.ndim - 1
                and jnp.issubdtype(jnp.asarray(lbl).dtype, jnp.integer)):
            # hot path (LLM loss): hard labels over the last dim with no
            # weights/smoothing — the memory-lean custom-vjp CE
            # (ops/kernels/fused_ce.py) avoids materializing any fp32
            # logits/softmax copy for backward. The per-token loss STAYS fp32
            # through the (tokens,)-sized masking/mean tail — it's free and
            # keeps the loss scalar + the mean's 1/count backward scale from
            # rounding through bf16; only a reduction='none' return is cast
            # back to the logits dtype for parity with the generic branch.
            from ...ops.kernels.fused_ce import fused_softmax_ce
            flat = fused_softmax_ce(logits.reshape(-1, n_classes),
                                    lbl_int.reshape(-1), ignore_index)
            loss = flat.reshape(lbl_int.shape)
            none_cast = logits.dtype
        else:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax) \
                if use_softmax \
                else jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
            if label_smoothing > 0.0:
                eps = label_smoothing
                nll = -jnp.take_along_axis(logp, jnp.expand_dims(
                    jnp.clip(lbl_int, 0, n_classes - 1), ax),
                    axis=ax).squeeze(ax)
                smooth = -jnp.mean(logp, axis=ax)
                loss = (1 - eps) * nll + eps * smooth
            else:
                loss = -jnp.take_along_axis(logp, jnp.expand_dims(
                    jnp.clip(lbl_int, 0, n_classes - 1), ax),
                    axis=ax).squeeze(ax)
            # generic branch keeps the same fp32-tail contract as the fused
            # path: the (tokens,)-sized tail is free in fp32 and reductions
            # must not change dtype depending on which branch ran
            none_cast = logits.dtype
        valid = (lbl_int != ignore_index)
        loss = jnp.where(valid, loss, 0.0)
        if w:
            cw = jnp.take(w[0], jnp.clip(lbl_int, 0, n_classes - 1))
            cw = jnp.where(valid, cw, 0.0)
            loss = loss * cw
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(cw), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        out = _reduce(loss, reduction)
        if none_cast is not None and reduction == "none":
            out = out.astype(none_cast)
        return out
    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch(fn, args, {}, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = dispatch(lambda l: jnp.expand_dims(l, int(axis)), (loss,), {},
                    name="unsqueeze")
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def fn(logp, lbl, *w):
        lbl_int = lbl.astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, jnp.expand_dims(
            jnp.clip(lbl_int, 0, logp.shape[1] - 1), 1), axis=1).squeeze(1)
        valid = lbl_int != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w:
            cw = jnp.take(w[0], jnp.clip(lbl_int, 0, logp.shape[1] - 1))
            cw = jnp.where(valid, cw, 0.0)
            loss = loss * cw
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(cw), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch(fn, args, {}, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return dispatch(lambda a, b: _reduce(jnp.square(a - b), reduction),
                    (input, label), {}, name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return dispatch(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    (input, label), {}, name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss * delta, reduction)
    return dispatch(fn, (input, label), {}, name="smooth_l1_loss")


def huber_loss(input, label, delta=1.0, reduction="mean"):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return dispatch(fn, (input, label), {}, name="huber_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, l, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(l * jnp.log(p) + (1 - l) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch(fn, args, {}, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, l, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        # stable: max(z,0) - z*l + log(1+exp(-|z|)), with pos_weight on the positive term
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            loss = -(pw * l * log_sig + (1 - l) * log_sig_neg)
        else:
            loss = jnp.maximum(z, 0) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = (logit, label) + tuple(t for t in (weight, pos_weight) if t is not None)
    return dispatch(fn, args, {}, name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(logp, t):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            loss = t * (jnp.log(jnp.maximum(t, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return dispatch(fn, (input, label), {}, name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, l):
        loss = jnp.maximum(0.0, -l * (a - b) + margin)
        return _reduce(loss, reduction)
    return dispatch(fn, (input, other, label), {}, name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(a, l):
        loss = jnp.where(l == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return dispatch(fn, (input, label), {}, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def fn(a, b, l):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(l == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return dispatch(fn, (input1, input2, label), {}, name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), axis=-1),
                             1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, d_pos - d_neg + margin), reduction)
    return dispatch(fn, (input, positive, negative), {}, name="triplet_margin_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, l):
        return -l * jnp.log(p + epsilon) - (1 - l) * jnp.log(1 - p + epsilon)
    return dispatch(fn, (input, label), {}, name="log_loss")


def square_error_cost(input, label):
    return dispatch(lambda a, b: jnp.square(a - b), (input, label), {},
                    name="square_error_cost")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, l, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * l + (1 - p) * (1 - l)
        a_t = alpha * l + (1 - alpha) * (1 - l)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return dispatch(fn, args, {}, name="sigmoid_focal_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward-alpha recursion in log space (lax.scan over time).
    Reference analog: warpctc (third_party) behind phi ctc kernels."""
    def fn(lp, lbl, in_len, lbl_len):
        # lp: [T, B, C] log-probs (paddle convention), lbl: [B, S]
        T, B, C = lp.shape
        S = lbl.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, dtype=lbl.dtype)
        ext = ext.at[:, 1::2].set(lbl)  # blank a1 blank a2 ... blank
        L = 2 * S + 1
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        def gather_probs(lp_t):
            return jnp.take_along_axis(lp_t, ext, axis=1)  # [B, L]

        alpha0 = jnp.full((B, L), neg_inf, lp.dtype)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lbl = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lbl)

        same_as_prev2 = jnp.concatenate(
            [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            probs = gather_probs(lp_t)
            shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf, lp.dtype),
                                      alpha[:, :-1]], axis=1)
            shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf, lp.dtype),
                                      alpha[:, :-2]], axis=1)
            shift2 = jnp.where(same_as_prev2, neg_inf, shift2)
            new = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2) + probs
            return new, new

        alpha_T, alphas = jax.lax.scan(step, alpha0, lp[1:])
        all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, L]
        t_idx = jnp.clip(in_len - 1, 0, T - 1)
        alpha_final = all_alphas[t_idx, jnp.arange(B)]  # [B, L]
        end1 = jnp.take_along_axis(alpha_final, (2 * lbl_len)[:, None], axis=1)[:, 0]
        end2 = jnp.take_along_axis(alpha_final,
                                   jnp.maximum(2 * lbl_len - 1, 0)[:, None],
                                   axis=1)[:, 0]
        ll = jnp.logaddexp(end1, end2)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len, 1).astype(loss.dtype))
        return _reduce(loss, reduction)
    return dispatch(fn, (log_probs, labels, input_lengths, label_lengths), {},
                    name="ctc_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-label*input)) (reference: nn/functional/loss.py);
    softplus form keeps large logits finite."""
    def fn(a, l):
        return _reduce(jax.nn.softplus(-l * a), reduction)
    return dispatch(fn, (input, label), {}, name="soft_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    def fn(a, l, w):
        loss = -(l * jax.nn.log_sigmoid(a)
                 + (1 - l) * jax.nn.log_sigmoid(-a))
        if w is not None:
            loss = loss * w
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    return dispatch(fn, (input, label, weight), {},
                    name="multi_label_soft_margin_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(a, l):
        if log_input:
            loss = jnp.exp(a) - l * a
        else:
            loss = a - l * jnp.log(a + epsilon)
        if full:
            # Stirling approximation for the label factorial term
            stirling = l * jnp.log(jnp.maximum(l, 1.0)) - l \
                + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(l, 1.0))
            loss = loss + jnp.where(l > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return dispatch(fn, (input, label), {}, name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, l, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(l - mu) / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)
    return dispatch(fn, (input, label, variance), {}, name="gaussian_nll_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    # user distance_function operates on framework Tensors
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        d_swap = distance_function(positive, negative)
        d_neg_v = dispatch(lambda a, b: jnp.minimum(a, b),
                           (d_neg, d_swap), {}, name="tmwd_min")
    else:
        d_neg_v = d_neg

    def fn(dp, dn):
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return dispatch(fn, (d_pos, d_neg_v), {},
                    name="triplet_margin_with_distance_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference: loss.py:50 — 1 - 2|X∩Y| / (|X|+|Y|), mean over batch."""

    def fn(inp, lbl):
        lbl = jnp.squeeze(lbl, -1)
        oh = jax.nn.one_hot(lbl, inp.shape[-1], dtype=inp.dtype)
        axes = tuple(range(1, inp.ndim))
        inse = jnp.sum(inp * oh, axis=axes)
        denom = jnp.sum(inp, axis=axes) + jnp.sum(oh, axis=axes)
        return jnp.mean(1 - 2 * inse / (denom + epsilon))
    return dispatch(fn, (input, label), {}, name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference: loss.py:346 — similarity-matrix soft-label CE + L2 term."""

    def fn(a, p, lab):
        bs = lab.shape[0]
        lab2 = jnp.tile(lab.reshape(bs, 1), (1, bs))
        eq = (lab2 == lab2.T).astype(jnp.float32)
        soft = eq / jnp.sum(eq, axis=1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(jnp.square(a), 1)) +
              jnp.mean(jnp.sum(jnp.square(p), 1))) * 0.25 * l2_reg
        sim = a @ p.T
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce_rows = -jnp.sum(soft * logp, axis=-1, keepdims=True)
        ce = jnp.mean(jnp.sum(soft * ce_rows, 0))
        return l2.astype(a.dtype) + ce
    return dispatch(fn, (anchor, positive, labels), {}, name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid over a complete binary tree (reference:
    loss.py hsigmoid_loss → phi hsigmoid_loss kernel; code scheme from
    funcs/matrix_bit_code.h SimpleCode: c = label + num_classes,
    index(b) = (c >> (b+1)) - 1, bit(b) = (c >> b) & 1)."""
    max_bits = max(1, int(np.ceil(np.log2(max(2, num_classes)))) + 1)

    def fn(x, lbl, w, b, ptab, pcode):
        lbl = lbl.reshape(-1)
        if ptab is not None:
            idx = ptab.astype(jnp.int32)           # (N, L)
            bits = pcode.astype(jnp.float32)       # (N, L)
            valid = idx >= 0
            idx = jnp.maximum(idx, 0)
        else:
            c = (lbl + num_classes).astype(jnp.int32)[:, None]  # (N, 1)
            brange = jnp.arange(max_bits, dtype=jnp.int32)[None, :]
            length = jnp.floor(
                jnp.log2(c.astype(jnp.float32))).astype(jnp.int32)
            valid = brange < length
            idx = jnp.clip((c >> (brange + 1)) - 1, 0, num_classes - 2)
            bits = ((c >> brange) & 1).astype(jnp.float32)
        wsel = w[idx]                              # (N, L, F)
        logits = jnp.einsum("nf,nlf->nl", x, wsel)
        if b is not None:
            logits = logits + b.reshape(-1)[idx]
        # sigmoid cross entropy: log(1+e^z) - t*z, summed over the code path
        per_bit = jnp.logaddexp(0.0, logits) - bits * logits
        loss = jnp.sum(jnp.where(valid, per_bit, 0.0), axis=1, keepdims=True)
        return loss.astype(x.dtype)
    return dispatch(fn, (input, label, weight, bias, path_table, path_code), {},
                    name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-family margin softmax (reference: loss.py:2223 →
    margin_cross_entropy kernel): target logit cos(m1·θ + m2) - m3, scaled."""
    if group is not None and group is not False:
        # the reference's group arg enables model-parallel margin softmax over
        # class-sharded logits; silently computing a local-shard-only result
        # would be wrong — shard the classes with fleet ParallelCrossEntropy
        # style TP instead
        raise NotImplementedError(
            "margin_cross_entropy(group=...) model-parallel margin softmax "
            "is not implemented; pass replicated logits (group=None)")

    def fn(lg, lbl):
        lbl_flat = lbl.reshape(-1)
        oh = jax.nn.one_hot(lbl_flat, lg.shape[-1], dtype=lg.dtype)
        cos_t = jnp.sum(lg * oh, axis=-1)
        theta = jnp.arccos(jnp.clip(cos_t.astype(jnp.float32), -1.0, 1.0))
        mod = jnp.cos(margin1 * theta + margin2) - margin3
        lg2 = lg.astype(jnp.float32) * (1 - oh) + mod[:, None] * oh
        lg2 = lg2 * scale
        logp = jax.nn.log_softmax(lg2, axis=-1)
        loss = -jnp.sum(oh * logp, axis=-1, keepdims=True).astype(lg.dtype)
        sm = jnp.exp(logp).astype(lg.dtype)
        return _reduce(loss, reduction), sm
    loss, sm = dispatch(fn, (logits, label), {}, name="margin_cross_entropy")
    if return_softmax:
        return loss, sm
    return loss


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference: loss.py multi_margin_loss — hinge over wrong classes."""

    def fn(x, lbl, w):
        lbl = lbl.reshape(-1)
        C = x.shape[1]
        oh = jax.nn.one_hot(lbl, C, dtype=x.dtype)
        target = jnp.sum(x * oh, axis=1, keepdims=True)
        hinge = jnp.maximum(0.0, margin - target + x) ** p
        if w is not None:
            hinge = hinge * w[lbl][:, None]
        loss = jnp.sum(hinge * (1 - oh), axis=1) / C
        return _reduce(loss, reduction)
    return dispatch(fn, (input, label, weight), {}, name="multi_margin_loss")


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference: loss.py adaptive_log_softmax_with_loss):
    frequent classes in the head, rare classes in projected tail clusters.
    Returns (per-sample target log-prob, mean NLL)."""
    n_clusters = len(cutoffs) - 1 if cutoffs[-1] is not None else len(cutoffs)
    shortlist = int(cutoffs[0])
    cut = [shortlist] + [int(c) for c in cutoffs[1:]]

    def fn(x, lbl, hw, hb, *tails):
        lbl = lbl.reshape(-1)
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_logp = jax.nn.log_softmax(head_logits, axis=-1)
        in_head = lbl < shortlist
        out = jnp.take_along_axis(
            head_logp, jnp.clip(lbl, 0, shortlist - 1)[:, None], axis=1)[:, 0]
        out = jnp.where(in_head, out, 0.0)
        for i in range(len(cut) - 1):
            proj, cls_w = tails[2 * i], tails[2 * i + 1]
            lo, hi = cut[i], cut[i + 1]
            tail_logp = jax.nn.log_softmax((x @ proj) @ cls_w, axis=-1)
            rel = jnp.clip(lbl - lo, 0, hi - lo - 1)
            cluster_lp = head_logp[:, shortlist + i] + \
                jnp.take_along_axis(tail_logp, rel[:, None], axis=1)[:, 0]
            out = jnp.where((lbl >= lo) & (lbl < hi), cluster_lp, out)
        return out, -jnp.mean(out)
    tails_flat = []
    for pair in tail_weights:
        tails_flat.extend(pair)
    return dispatch(fn, (input, label, head_weight, head_bias, *tails_flat), {},
                    name="adaptive_log_softmax_with_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (reference: loss.py rnnt_loss → warprnnt). Forward
    log-alpha DP over the (T, U) lattice with lax.scan; gradients come from
    autodiff through the DP (the analytic beta recursion the CUDA lib uses is
    exactly the adjoint of this scan).

    fastemit_lambda: the reference's warprnnt applies FastEmit GRADIENT
    reweighting (scale the label-emission adjoint by 1+lambda) without
    changing the loss value; autodiff of this DP yields the unregularized
    gradients, so a nonzero lambda is refused rather than silently ignored.
    """
    if fastemit_lambda:
        import warnings
        warnings.warn(
            "rnnt_loss: fastemit_lambda != 0 requested but FastEmit gradient "
            "reweighting is not implemented — training proceeds with the "
            "UNREGULARIZED rnnt gradient (loss values are identical)",
            RuntimeWarning, stacklevel=2)

    def fn(logits, lbl, in_len, lbl_len):
        if logits.ndim == 3:
            logits_b = logits[None]
            lbl_b = lbl[None]
            in_len_b = in_len.reshape(1)
            lbl_len_b = lbl_len.reshape(1)
        else:
            logits_b, lbl_b = logits, lbl
            in_len_b, lbl_len_b = in_len.reshape(-1), lbl_len.reshape(-1)
        B, T, U, V = logits_b.shape
        logp = jax.nn.log_softmax(logits_b.astype(jnp.float32), axis=-1)
        blank_lp = logp[..., blank]                      # (B, T, U)
        NEG = jnp.asarray(-1e30, jnp.float32)
        if U > 1:
            lbl_idx = jnp.clip(lbl_b, 0, V - 1)          # (B, U-1)
            yp = jnp.take_along_axis(
                logp[:, :, : U - 1, :],
                jnp.broadcast_to(lbl_idx[:, None, :, None], (B, T, U - 1, 1)),
                axis=-1)[..., 0]                         # (B, T, U-1) label emission
        else:
            # empty transcript: no label emissions, only the blank path
            yp = jnp.full((B, T, 1), NEG)

        def t_step(alpha_prev, t):
            # alpha over u for fixed t; scan emission over u via prefix DP
            # alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
            #                          alpha[t, u-1] + y[t, u-1])
            from_blank = jnp.where(
                t > 0, alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0), :], NEG)
            from_blank = jnp.where(t > 0, from_blank,
                                   jnp.where(jnp.arange(U) == 0, 0.0, NEG))

            def u_step(carry, u):
                horiz = jnp.where(
                    u > 0,
                    carry + yp[:, t, jnp.clip(u - 1, 0, yp.shape[2] - 1)], NEG)
                a = jnp.logaddexp(from_blank[:, u], horiz)
                a = jnp.where((t == 0) & (u == 0), 0.0, a)
                return a, a
            _, cols = jax.lax.scan(u_step, jnp.full((B,), NEG), jnp.arange(U))
            alpha_t = jnp.moveaxis(cols, 0, 1)           # (B, U)
            return alpha_t, alpha_t

        _, alphas = jax.lax.scan(t_step, jnp.full((B, U), NEG), jnp.arange(T))
        alphas = jnp.moveaxis(alphas, 0, 1)              # (B, T, U)
        bidx = jnp.arange(B)
        t_last = jnp.clip(in_len_b - 1, 0, T - 1)
        u_last = jnp.clip(lbl_len_b, 0, U - 1)
        total = alphas[bidx, t_last, u_last] + blank_lp[bidx, t_last, u_last]
        loss = -total
        if logits.ndim == 3:
            loss = loss[0]
        return _reduce(loss, reduction)
    return dispatch(fn, (input, label, input_lengths, label_lengths), {},
                    name="rnnt_loss")
