"""Activation functionals (paddle.nn.functional activation analog).

Reference: python/paddle/nn/functional/activation.py → phi activation kernels.
All are single jnp/jax.nn expressions; XLA fuses them into neighboring ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import dispatch


def _unary(name, fn):
    def op(x, name_arg=None):
        return dispatch(fn, (x,), {}, name=name)
    op.__name__ = name
    return op


relu = _unary("relu", jax.nn.relu)
relu6 = _unary("relu6", jax.nn.relu6)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
tanh = _unary("tanh", jnp.tanh)
silu = _unary("silu", jax.nn.silu)
swish = silu
mish = _unary("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
tanhshrink = _unary("tanhshrink", lambda x: x - jnp.tanh(x))
softsign = _unary("softsign", jax.nn.soft_sign)


def gelu(x, approximate=False, name=None):
    return dispatch(lambda v: jax.nn.gelu(v, approximate=approximate), (x,), {},
                    name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    return dispatch(lambda v: jax.nn.leaky_relu(v, negative_slope), (x,), {},
                    name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_axis = 1 if data_format[1] == "C" else v.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)
    return dispatch(fn, (x, weight), {}, name="prelu")


def elu(x, alpha=1.0, name=None):
    return dispatch(lambda v: jax.nn.elu(v, alpha), (x,), {}, name="elu")


def celu(x, alpha=1.0, name=None):
    return dispatch(lambda v: jax.nn.celu(v, alpha), (x,), {}, name="celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return dispatch(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
                    (x,), {}, name="selu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return dispatch(lambda v: jnp.clip(v, min, max), (x,), {}, name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return dispatch(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0).astype(v.dtype),
                    (x,), {}, name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    def fn(v):
        return jnp.where(v > threshold, v - threshold,
                         jnp.where(v < -threshold, v + threshold, 0.0)).astype(v.dtype)
    return dispatch(fn, (x,), {}, name="softshrink")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return dispatch(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0).astype(v.dtype),
                    (x,), {}, name="hardsigmoid")


def hardswish(x, name=None):
    return dispatch(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, (x,), {},
                    name="hardswish")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    def fn(v):
        bv = beta * v
        return jnp.where(bv > threshold, v, jnp.log1p(jnp.exp(bv)) / beta)
    return dispatch(fn, (x,), {}, name="softplus")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return dispatch(lambda v: jnp.where(v > threshold, v, value).astype(v.dtype),
                    (x,), {}, name="thresholded_relu")


def log_sigmoid(x, name=None):
    return dispatch(jax.nn.log_sigmoid, (x,), {}, name="log_sigmoid")


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return dispatch(fn, (x,), {}, name="maxout")


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            import numpy as np
            v = v.astype(np.dtype(dtype) if not isinstance(dtype, str) else dtype)
        return jax.nn.softmax(v, axis=int(axis))
    return dispatch(fn, (x,), {}, name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            import numpy as np
            v = v.astype(np.dtype(dtype) if not isinstance(dtype, str) else dtype)
        return jax.nn.log_softmax(v, axis=int(axis))
    return dispatch(fn, (x,), {}, name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as _random

    def fn(v):
        g = jax.random.gumbel(_random.next_key(), v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            y_hard = jax.nn.one_hot(idx, v.shape[axis], axis=axis, dtype=y.dtype)
            y = jax.lax.stop_gradient(y_hard - y) + y  # straight-through estimator
        return y
    return dispatch(fn, (x,), {}, name="gumbel_softmax")


def glu(x, axis=-1, name=None):
    return dispatch(lambda v: jax.nn.glu(v, axis=int(axis)), (x,), {}, name="glu")


def swiglu(x, y=None, name=None):
    """paddle.incubate.nn.functional.swiglu analog: silu(x) * y (or split last dim)."""
    if y is None:
        return dispatch(lambda v: (lambda a, b: jax.nn.silu(a) * b)(
            *jnp.split(v, 2, axis=-1)), (x,), {}, name="swiglu")
    return dispatch(lambda a, b: jax.nn.silu(a) * b, (x, y), {}, name="swiglu")


def rrelu(x, lower=0.125, upper=0.3333333, training=False, name=None):
    from ...core import random as _random

    def fn(v):
        if training:
            a = jax.random.uniform(_random.next_key(), v.shape, jnp.float32,
                                   lower, upper).astype(v.dtype)
        else:
            a = jnp.asarray((lower + upper) / 2.0, v.dtype)
        return jnp.where(v >= 0, v, a * v)
    return dispatch(fn, (x,), {}, name="rrelu")


def _inplace_variant(fn, op_name):
    """paddle's `<act>_` in-place forms: write the result back into x's buffer
    (our Tensors are jax.Array façades, so "in place" = rebind the value and
    keep the autograd linkage, same as the top-level paddle_tpu._inplace)."""
    def op(x, *args, **kwargs):
        kwargs.pop("name", None)
        out = fn(x, *args, **kwargs)
        x._value = out._value
        x._node = out._node
        x._out_index = out._out_index
        if not out.stop_gradient:
            x.stop_gradient = False
        return x
    op.__name__ = op_name
    return op


relu_ = _inplace_variant(relu, "relu_")
tanh_ = _inplace_variant(tanh, "tanh_")
elu_ = _inplace_variant(elu, "elu_")
hardtanh_ = _inplace_variant(hardtanh, "hardtanh_")
leaky_relu_ = _inplace_variant(leaky_relu, "leaky_relu_")
softmax_ = _inplace_variant(softmax, "softmax_")
thresholded_relu_ = _inplace_variant(thresholded_relu, "thresholded_relu_")
