"""Common functionals: linear, dropout, embedding, pad, interpolate, etc.

Reference: python/paddle/nn/functional/common.py, input.py →
phi kernels (matmul+bias epilogue, dropout, embedding lookup). On TPU the
linear+bias+activation chain fuses in XLA; dropout keys ride core/random.py so eager
and jit-traced paths are both reproducible.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import random as _random
from ...core.tensor import Tensor, dispatch


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. Weight layout [in, out] (paddle convention)."""
    if bias is None:
        return dispatch(lambda v, w: v @ w, (x, weight), {}, name="linear")
    return dispatch(lambda v, w, b: v @ w + b, (x, weight, bias), {}, name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    key = _random.next_key()

    def fn(v):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in [a % v.ndim for a in axes] else 1
                     for i, s in enumerate(v.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)
    return dispatch(fn, (x,), {}, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def _alpha_dropout_impl(x, p, mask_shape_fn, op_name):
    """Shared SELU-preserving dropout math; mask_shape_fn(v) picks element- vs
    channel-wise masking."""
    key = _random.next_key()

    def fn(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape_fn(v))
        a = (1.0 / np.sqrt((1.0 - p) * (1.0 + p * alpha_p ** 2)))
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return dispatch(fn, (x,), {}, name=op_name)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    return _alpha_dropout_impl(x, p, lambda v: v.shape, "alpha_dropout")


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout zeroing whole (N, C) channels (reference:
    nn/functional/common.py feature_alpha_dropout)."""
    if not training or p == 0.0:
        return x
    return _alpha_dropout_impl(
        x, p, lambda v: v.shape[:2] + (1,) * (v.ndim - 2),
        "feature_alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def fn(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return dispatch(fn, (x, weight), {}, name="embedding")


def one_hot(x, num_classes, name=None):
    return dispatch(lambda v: jax.nn.one_hot(v, int(num_classes), dtype=jnp.float32),
                    (x,), {}, name="one_hot")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, *rest):
        n = l.shape[-1]
        if rest:
            return (1 - epsilon) * l + epsilon * rest[0]
        return (1 - epsilon) * l + epsilon / n
    args = (label,) + ((prior_dist,) if prior_dist is not None else ())
    return dispatch(fn, args, {}, name="label_smooth")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True,
        name=None):
    """paddle.nn.functional.pad — int-list pad in reversed-last-dims order for the
    NCHW/NCL/NCDHW forms, or full per-dim pairs when len(pad) == 2*ndim."""
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def fn(v):
        nd = v.ndim
        width = [(0, 0)] * nd
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if len(pad) == 2 * nd:
            if pad_from_left_axis:
                width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
            else:
                width = [(pad[2 * (nd - 1 - i)], pad[2 * (nd - 1 - i) + 1])
                         for i in range(nd)]
        else:
            # data_format form: pad applies to spatial dims, last-dim-first pairs
            n_spatial = len(pad) // 2
            if data_format.endswith("C"):  # NLC / NHWC / NDHWC
                spatial = list(range(1, 1 + n_spatial))
            else:  # NCL / NCHW / NCDHW
                spatial = list(range(2, 2 + n_spatial))
            for i, d in enumerate(reversed(spatial)):
                if d >= nd:
                    raise ValueError(
                        f"pad: a {len(pad)}-element pad list is the "
                        f"{data_format} spatial form and needs rank >= "
                        f"{d + 1}, got rank {nd}; pass 2*ndim pairs for "
                        f"arbitrary tensors")
                width[d] = (pad[2 * i], pad[2 * i + 1])
        if jmode == "constant":
            return jnp.pad(v, width, mode="constant", constant_values=value)
        return jnp.pad(v, width, mode=jmode)
    return dispatch(fn, (x,), {}, name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: phi/kernels/unfold_kernel). NCHW only."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings) if not (isinstance(paddings, (list, tuple))
                                     and len(paddings) == 4) else (None, None)
    dh, dw = _pair(dilations)

    def fn(v):
        n, c, h, w = v.shape
        if ph is not None:
            vp = jnp.pad(v, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
        else:
            pt, pl, pb, pr = paddings
            vp = jnp.pad(v, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
        hh, ww = vp.shape[2], vp.shape[3]
        out_h = (hh - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (ww - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            vp, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * kh * kw, out_h * out_w)
    return dispatch(fn, (x,), {}, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)

    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        out_h = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        cols = v.reshape(n, c, kh, kw, out_h, out_w)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), v.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wi = j * dw
                out = out.at[:, :, hi:hi + sh * out_h:sh, wi:wi + sw * out_w:sw].add(
                    cols[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]
    return dispatch(fn, (x,), {}, name="fold")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    """paddle.nn.functional.interpolate via jax.image.resize."""
    def fn(v):
        channel_last = data_format.endswith("C")
        nd = v.ndim - 2
        spatial = v.shape[1:-1] if channel_last else v.shape[2:]
        if size is not None:
            tgt = [int(s._value) if isinstance(s, Tensor) else int(s)
                   for s in (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * nd
            tgt = [int(round(s * f)) for s, f in zip(spatial, sf)]
        method = {"nearest": "nearest", "bilinear": "bilinear", "trilinear": "trilinear",
                  "bicubic": "bicubic", "linear": "linear", "area": "linear"}[mode]
        if channel_last:
            new_shape = (v.shape[0], *tgt, v.shape[-1])
        else:
            new_shape = (v.shape[0], v.shape[1], *tgt)
        return jax.image.resize(v, new_shape, method=method).astype(v.dtype)
    return dispatch(fn, (x,), {}, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = int(upscale_factor)

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return dispatch(fn, (x,), {}, name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h // r, w // r, c * r * r)
    return dispatch(fn, (x,), {}, name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    g = int(groups)

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            return v.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4) \
                .reshape(n, c, h, w)
        n, h, w, c = v.shape
        return v.reshape(n, h, w, g, c // g).transpose(0, 1, 2, 4, 3) \
            .reshape(n, h, w, c)
    return dispatch(fn, (x,), {}, name="channel_shuffle")


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return dispatch(fn, args, {}, name="bilinear")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return dispatch(fn, (x1, x2), {}, name="cosine_similarity")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        norm = jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=axis, keepdims=True),
                         1.0 / p)
        return v / jnp.maximum(norm, epsilon)
    return dispatch(fn, (x,), {}, name="normalize")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """||x - y + eps||_p along the last axis (reference: functional/distance.py)."""
    def fn(a, b):
        d = jnp.abs(a - b) + epsilon
        return jnp.power(jnp.sum(jnp.power(d, p), axis=-1, keepdims=keepdim),
                         1.0 / p)
    return dispatch(fn, (x, y), {}, name="pairwise_distance")
