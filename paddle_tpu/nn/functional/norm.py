"""Normalization functionals.

Reference: python/paddle/nn/functional/norm.py → phi layer_norm/batch_norm/group_norm
kernels (hand-written Welford/CUB reductions). TPU-native: plain jnp reductions — XLA
fuses mean/var/normalize into one kernel; rms_norm additionally has a Pallas fast path
(ops/kernels/rms_norm.py) used on TPU for the fused residual+cast cases.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    ndims = len(tuple(normalized_shape))
    # close over booleans, not the weight/bias Tensors themselves: a Tensor in
    # the closure blocks the compiled dispatch cache (core/tensor.py _freeze)
    has_w, has_b = weight is not None, bias is not None

    def fn(v, *wb):
        axes = tuple(range(v.ndim - ndims, v.ndim))
        # reduce in fp32 for bf16 inputs (matches reference's fp32 accumulators)
        compute = v.astype(jnp.float32) if v.dtype in (jnp.bfloat16, jnp.float16) else v
        mean = jnp.mean(compute, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(compute - mean), axis=axes, keepdims=True)
        out = (compute - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch(fn, args, {}, name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference: incubate/nn/functional/fused_rms_norm.py)."""
    def fn(v, *w):
        compute = v.astype(jnp.float32) if v.dtype in (jnp.bfloat16, jnp.float16) else v
        ms = jnp.mean(jnp.square(compute), axis=-1, keepdims=True)
        out = (compute * jax.lax.rsqrt(ms + epsilon)).astype(v.dtype)
        if w:
            out = out * w[0]
        return out
    args = (x,) + ((weight,) if weight is not None else ())
    return dispatch(fn, args, {}, name="rms_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               name=None):
    """Batch normalization with running-stat updates.

    In eager mode the running stats (buffers) are updated in place; under a jit trace
    the updated values are traced arrays captured by the functional-state machinery
    (jit/functional_call.py) — the analog of the reference's in-kernel stat writes.
    """
    channel_axis = 1 if not data_format.endswith("C") or data_format == "NCHW" else -1
    use_stats = (not training) if use_global_stats is None else use_global_stats

    rm = running_mean._value if isinstance(running_mean, Tensor) else running_mean
    rv = running_var._value if isinstance(running_var, Tensor) else running_var

    def fn(v, *wb):
        c_ax = channel_axis % v.ndim
        axes = tuple(i for i in range(v.ndim) if i != c_ax)
        shape = [1] * v.ndim
        shape[c_ax] = v.shape[c_ax]
        compute = v.astype(jnp.float32) if v.dtype in (jnp.bfloat16, jnp.float16) else v
        if use_stats:
            mean, var = rm, rv
        else:
            mean = jnp.mean(compute, axis=axes)
            var = jnp.var(compute, axis=axes)
        out = (compute - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out, mean, var

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    out, batch_mean, batch_var = dispatch(fn, args, {}, name="batch_norm")

    if training and not use_stats and isinstance(running_mean, Tensor):
        from ..layer_base import Layer  # noqa: F401 (doc anchor)
        m = momentum
        running_mean._value = (m * rm + (1 - m) * batch_mean._value).astype(rm.dtype)
        running_var._value = (m * rv + (1 - m) * batch_var._value).astype(rv.dtype)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW",
                  name=None):
    def fn(v, *wb):
        axes = tuple(range(2, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch(fn, args, {}, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    g = int(num_groups)

    def fn(v, *wb):
        if data_format.endswith("C") and data_format != "NCHW":
            v_ = jnp.moveaxis(v, -1, 1)
        else:
            v_ = v
        n, c = v_.shape[0], v_.shape[1]
        spatial = v_.shape[2:]
        r = v_.reshape(n, g, c // g, *spatial)
        axes = tuple(range(2, r.ndim))
        compute = r.astype(jnp.float32) if r.dtype in (jnp.bfloat16, jnp.float16) else r
        mean = jnp.mean(compute, axis=axes, keepdims=True)
        var = jnp.var(compute, axis=axes, keepdims=True)
        out = ((compute - mean) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        out = out.reshape(v_.shape)
        shape = [1, c] + [1] * (v_.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if data_format.endswith("C") and data_format != "NCHW":
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch(fn, args, {}, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                        name=None):
    def fn(v):
        c_ax = 1 if data_format == "NCHW" or not data_format.endswith("C") else v.ndim - 1
        sq = jnp.square(v)
        half = size // 2
        pads = [(0, 0)] * v.ndim
        pads[c_ax] = (half, size - half - 1)
        window = [1] * v.ndim
        window[c_ax] = size
        summed = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(window),
                                       (1,) * v.ndim, pads)
        return v / jnp.power(k + alpha * summed / size, beta)
    return dispatch(fn, (x,), {}, name="local_response_norm")
