"""paddle.nn.functional analog."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose, conv3d_transpose,
)
from .pooling import *  # noqa: F401,F403
from .norm import (  # noqa: F401
    layer_norm, rms_norm, batch_norm, instance_norm, group_norm, local_response_norm,
)
from .loss import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    scaled_dot_product_attention, flash_attention, flash_attn_unpadded,
    flashmask_attention, flash_attn_qkvpacked, flash_attn_varlen_qkvpacked,
)
from .extension import (  # noqa: F401
    sequence_mask, temporal_shift, affine_grid, grid_sample, gather_tree,
    class_center_sample, sparse_attention,
)
