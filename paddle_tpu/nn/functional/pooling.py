"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py →
phi pool kernels). TPU-native: lax.reduce_window, which XLA lowers to fused
windowed reductions.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import dispatch


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _pool(x, kernel, stride, padding, n, reducer, init_scalar, channel_last,
          ceil_mode=False, count_include_pad=True, divisor_override=None,
          is_avg=False, exclusive=True):
    k = _tuple(kernel, n)
    s = _tuple(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        p = [(0, 0)] * n
    else:
        pad_mode = None
        p = [(pp, pp) for pp in _tuple(padding, n)]

    def fn(v):
        nd = v.ndim
        if channel_last:
            window = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            pads = [(0, 0)] + p + [(0, 0)]
        else:
            window = (1, 1) + k
            strides = (1, 1) + s
            pads = [(0, 0), (0, 0)] + p
        if pad_mode == "SAME":
            spatial = v.shape[1:-1] if channel_last else v.shape[2:]
            pads2 = []
            for i in range(n):
                out_sz = -(-spatial[i] // s[i])
                total = max(0, (out_sz - 1) * s[i] + k[i] - spatial[i])
                pads2.append((total // 2, total - total // 2))
            pads = ([(0, 0)] + pads2 + [(0, 0)]) if channel_last \
                else [(0, 0), (0, 0)] + pads2
        if ceil_mode:
            spatial_axes = range(1, 1 + n) if channel_last else range(2, 2 + n)
            for i, ax in enumerate(spatial_axes):
                size = v.shape[ax] + pads[ax][0] + pads[ax][1]
                rem = (size - k[i]) % s[i]
                if rem != 0:
                    pads[ax] = (pads[ax][0], pads[ax][1] + (s[i] - rem))
        if is_avg:
            summed = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides, pads)
            if divisor_override:
                return summed / divisor_override
            if exclusive and any(pp != (0, 0) for pp in pads):
                ones = jnp.ones_like(v)
                counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                               window, strides, pads)
                return summed / counts
            return summed / float(np.prod(k))
        return jax.lax.reduce_window(v, init_scalar, reducer, window, strides, pads)
    return fn


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fn = _pool(x, kernel_size, stride, padding, 1, jax.lax.max,
               -jnp.inf, data_format.endswith("C") and
               data_format != "NCL", ceil_mode)
    return dispatch(fn, (x,), {}, name="max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    fn = _pool(x, kernel_size, stride, padding, 2, jax.lax.max,
               -jnp.inf, data_format == "NHWC", ceil_mode)
    out = dispatch(fn, (x,), {}, name="max_pool2d")
    if return_mask:
        idx = _max_pool_mask(x, kernel_size, stride, padding, data_format)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    fn = _pool(x, kernel_size, stride, padding, 3, jax.lax.max,
               -jnp.inf, data_format == "NDHWC", ceil_mode)
    return dispatch(fn, (x,), {}, name="max_pool3d")


def _max_pool_mask(x, kernel_size, stride, padding, data_format):
    from ...core.tensor import Tensor
    k = _tuple(kernel_size, 2)
    s = _tuple(stride if stride is not None else kernel_size, 2)
    p = _tuple(padding, 2)

    def fn(v):
        n, c, h, w = v.shape
        hw = h * w
        idx = jnp.arange(hw, dtype=jnp.float32).reshape(1, 1, h, w)
        idx = jnp.broadcast_to(idx, v.shape)
        # select argmax index via reduce_window over (value, index) pairs
        def red(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv > av
            return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
        init = (jnp.asarray(-jnp.inf, v.dtype), jnp.asarray(-1.0))
        vv, ii = jax.lax.reduce_window((v, idx), init, red,
                                       (1, 1) + k, (1, 1) + s,
                                       [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
        return ii.astype(jnp.int32)
    return dispatch(fn, (x,), {}, name="max_pool2d_mask")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fn = _pool(x, kernel_size, stride, padding, 1, jax.lax.add,
               0.0, False, ceil_mode, is_avg=True,
               exclusive=exclusive)
    return dispatch(fn, (x,), {}, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    fn = _pool(x, kernel_size, stride, padding, 2, jax.lax.add,
               0.0, data_format == "NHWC", ceil_mode,
               is_avg=True, divisor_override=divisor_override, exclusive=exclusive)
    return dispatch(fn, (x,), {}, name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    fn = _pool(x, kernel_size, stride, padding, 3, jax.lax.add,
               0.0, data_format == "NDHWC", ceil_mode,
               is_avg=True, divisor_override=divisor_override, exclusive=exclusive)
    return dispatch(fn, (x,), {}, name="avg_pool3d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    pw = float(norm_type)

    def fn(v):
        powed = jnp.power(jnp.abs(v), pw)
        pool = _pool(None, kernel_size, stride, padding, 2, jax.lax.add,
                     0.0, data_format == "NHWC", ceil_mode,
                     is_avg=False)(powed)
        return jnp.power(pool, 1.0 / pw)
    return dispatch(fn, (x,), {}, name="lp_pool2d")


def _adaptive_axes(in_sz, out_sz):
    # exact adaptive pooling: split with variable windows via cumulative segments
    starts = (np.arange(out_sz) * in_sz) // out_sz
    ends = -(-((np.arange(out_sz) + 1) * in_sz) // out_sz)
    return starts, ends


def _adaptive_pool(x, output_size, n, mode, channel_last):
    def fn(v):
        spatial_axes = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
        out_szs = _tuple(output_size, n)
        out = v
        for dim_i, ax in enumerate(spatial_axes):
            in_sz = out.shape[ax]
            o = out_szs[dim_i]
            if o is None:
                continue
            if in_sz % o == 0:
                # uniform window: reshape+reduce (fast path)
                kshape = list(out.shape)
                kshape[ax] = o
                kshape.insert(ax + 1, in_sz // o)
                r = out.reshape(kshape)
                out = (jnp.max(r, axis=ax + 1) if mode == "max"
                       else jnp.mean(r, axis=ax + 1))
            else:
                starts, ends = _adaptive_axes(in_sz, o)
                segs = []
                for si, ei in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(si), int(ei), axis=ax)
                    segs.append(jnp.max(seg, axis=ax, keepdims=True) if mode == "max"
                                else jnp.mean(seg, axis=ax, keepdims=True))
                out = jnp.concatenate(segs, axis=ax)
        return out
    return fn


def adaptive_avg_pool1d(x, output_size, name=None):
    return dispatch(_adaptive_pool(x, output_size, 1, "avg", False), (x,), {},
                    name="adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return dispatch(_adaptive_pool(x, output_size, 2, "avg", data_format == "NHWC"),
                    (x,), {}, name="adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return dispatch(_adaptive_pool(x, output_size, 3, "avg", data_format == "NDHWC"),
                    (x,), {}, name="adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return dispatch(_adaptive_pool(x, output_size, 1, "max", False), (x,), {},
                    name="adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return dispatch(_adaptive_pool(x, output_size, 2, "max", False), (x,), {},
                    name="adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return dispatch(_adaptive_pool(x, output_size, 3, "max", False), (x,), {},
                    name="adaptive_max_pool3d")
