"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py →
phi pool kernels). TPU-native: lax.reduce_window, which XLA lowers to fused
windowed reductions.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import dispatch


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _pool(x, kernel, stride, padding, n, reducer, init_scalar, channel_last,
          ceil_mode=False, count_include_pad=True, divisor_override=None,
          is_avg=False, exclusive=True):
    k = _tuple(kernel, n)
    s = _tuple(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        p = [(0, 0)] * n
    else:
        pad_mode = None
        p = [(pp, pp) for pp in _tuple(padding, n)]

    def fn(v):
        nd = v.ndim
        if channel_last:
            window = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            pads = [(0, 0)] + p + [(0, 0)]
        else:
            window = (1, 1) + k
            strides = (1, 1) + s
            pads = [(0, 0), (0, 0)] + p
        if pad_mode == "SAME":
            spatial = v.shape[1:-1] if channel_last else v.shape[2:]
            pads2 = []
            for i in range(n):
                out_sz = -(-spatial[i] // s[i])
                total = max(0, (out_sz - 1) * s[i] + k[i] - spatial[i])
                pads2.append((total // 2, total - total // 2))
            pads = ([(0, 0)] + pads2 + [(0, 0)]) if channel_last \
                else [(0, 0), (0, 0)] + pads2
        if ceil_mode:
            spatial_axes = range(1, 1 + n) if channel_last else range(2, 2 + n)
            for i, ax in enumerate(spatial_axes):
                size = v.shape[ax] + pads[ax][0] + pads[ax][1]
                rem = (size - k[i]) % s[i]
                if rem != 0:
                    pads[ax] = (pads[ax][0], pads[ax][1] + (s[i] - rem))
        if is_avg:
            summed = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides, pads)
            if divisor_override:
                return summed / divisor_override
            if exclusive and any(pp != (0, 0) for pp in pads):
                ones = jnp.ones_like(v)
                counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                               window, strides, pads)
                return summed / counts
            return summed / float(np.prod(k))
        return jax.lax.reduce_window(v, init_scalar, reducer, window, strides, pads)
    return fn


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fn = _pool(x, kernel_size, stride, padding, 1, jax.lax.max,
               -jnp.inf, data_format.endswith("C") and
               data_format != "NCL", ceil_mode)
    out = dispatch(fn, (x,), {}, name="max_pool1d")
    if return_mask:
        return out, _max_pool_mask(
            x, kernel_size, stride, padding, data_format, nd=1,
            ceil_mode=ceil_mode,
            channel_last=data_format.endswith("C")
            and data_format != "NCL")
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    fn = _pool(x, kernel_size, stride, padding, 2, jax.lax.max,
               -jnp.inf, data_format == "NHWC", ceil_mode)
    out = dispatch(fn, (x,), {}, name="max_pool2d")
    if return_mask:
        idx = _max_pool_mask(x, kernel_size, stride, padding, data_format,
                             nd=2, ceil_mode=ceil_mode,
                             channel_last=data_format == "NHWC")
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    fn = _pool(x, kernel_size, stride, padding, 3, jax.lax.max,
               -jnp.inf, data_format == "NDHWC", ceil_mode)
    out = dispatch(fn, (x,), {}, name="max_pool3d")
    if return_mask:
        return out, _max_pool_mask(
            x, kernel_size, stride, padding, data_format, nd=3,
            ceil_mode=ceil_mode, channel_last=data_format == "NDHWC")
    return out


def _max_pool_mask(x, kernel_size, stride, padding, data_format, nd=2,
                   ceil_mode=False, channel_last=False):
    """Flattened-spatial argmax indices for max_pool{1,2,3}d
    (return_mask=True) — what max_unpool{n}d consumes. MIRRORS _pool's
    window configuration exactly (string padding, ceil_mode,
    channel-last) so the mask always shapes like the pooled output."""
    from ...core.tensor import Tensor
    k = _tuple(kernel_size, nd)
    s = _tuple(stride if stride is not None else kernel_size, nd)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        p = [(0, 0)] * nd
    else:
        pad_mode = None
        p = [(pp, pp) for pp in _tuple(padding, nd)]

    def fn(v):
        if channel_last:
            # compute in channel-FIRST so the flattened spatial index
            # convention matches the unpool consumers, then move back
            v = jnp.moveaxis(v, -1, 1)
        spatial = v.shape[2:]
        pads = list(p)
        if pad_mode == "SAME":
            pads = []
            for i in range(nd):
                out_sz = -(-spatial[i] // s[i])
                total = max(0, (out_sz - 1) * s[i] + k[i] - spatial[i])
                pads.append((total // 2, total - total // 2))
        if ceil_mode:
            for i in range(nd):
                size = spatial[i] + pads[i][0] + pads[i][1]
                rem = (size - k[i]) % s[i]
                if rem != 0:
                    pads[i] = (pads[i][0], pads[i][1] + (s[i] - rem))
        size = 1
        for d in spatial:
            size *= d
        if size >= 2 ** 31:
            raise ValueError(
                f"max_pool return_mask: flattened spatial size {size} "
                f"overflows the int32 index space (2**31)")
        # int32 indices through the variadic reduce_window: a float32
        # carry is only exact up to 2**24, so spatial sizes above 16.7M
        # elements silently rounded the returned argmax positions
        idx = jnp.arange(size, dtype=jnp.int32).reshape(
            (1, 1) + tuple(spatial))
        idx = jnp.broadcast_to(idx, v.shape)
        # select argmax index via reduce_window over (value, index) pairs
        def red(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv > av
            return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)
        init = (jnp.asarray(-jnp.inf, v.dtype),
                jnp.asarray(-1, jnp.int32))
        vv, ii = jax.lax.reduce_window(
            (v, idx), init, red, (1, 1) + k, (1, 1) + s,
            [(0, 0), (0, 0)] + pads)
        if channel_last:
            ii = jnp.moveaxis(ii, 1, -1)
        return ii
    return dispatch(fn, (x,), {}, name=f"max_pool{nd}d_mask")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fn = _pool(x, kernel_size, stride, padding, 1, jax.lax.add,
               0.0, False, ceil_mode, is_avg=True,
               exclusive=exclusive)
    return dispatch(fn, (x,), {}, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    fn = _pool(x, kernel_size, stride, padding, 2, jax.lax.add,
               0.0, data_format == "NHWC", ceil_mode,
               is_avg=True, divisor_override=divisor_override, exclusive=exclusive)
    return dispatch(fn, (x,), {}, name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    fn = _pool(x, kernel_size, stride, padding, 3, jax.lax.add,
               0.0, data_format == "NDHWC", ceil_mode,
               is_avg=True, divisor_override=divisor_override, exclusive=exclusive)
    return dispatch(fn, (x,), {}, name="avg_pool3d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    pw = float(norm_type)

    def fn(v):
        powed = jnp.power(jnp.abs(v), pw)
        pool = _pool(None, kernel_size, stride, padding, 2, jax.lax.add,
                     0.0, data_format == "NHWC", ceil_mode,
                     is_avg=False)(powed)
        return jnp.power(pool, 1.0 / pw)
    return dispatch(fn, (x,), {}, name="lp_pool2d")


def _adaptive_axes(in_sz, out_sz):
    # exact adaptive pooling: split with variable windows via cumulative segments
    starts = (np.arange(out_sz) * in_sz) // out_sz
    ends = -(-((np.arange(out_sz) + 1) * in_sz) // out_sz)
    return starts, ends


def _adaptive_pool(x, output_size, n, mode, channel_last):
    def fn(v):
        spatial_axes = list(range(1, 1 + n)) if channel_last else list(range(2, 2 + n))
        out_szs = _tuple(output_size, n)
        out = v
        for dim_i, ax in enumerate(spatial_axes):
            in_sz = out.shape[ax]
            o = out_szs[dim_i]
            if o is None:
                continue
            if in_sz % o == 0:
                # uniform window: reshape+reduce (fast path)
                kshape = list(out.shape)
                kshape[ax] = o
                kshape.insert(ax + 1, in_sz // o)
                r = out.reshape(kshape)
                out = (jnp.max(r, axis=ax + 1) if mode == "max"
                       else jnp.mean(r, axis=ax + 1))
            else:
                starts, ends = _adaptive_axes(in_sz, o)
                segs = []
                for si, ei in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(si), int(ei), axis=ax)
                    segs.append(jnp.max(seg, axis=ax, keepdims=True) if mode == "max"
                                else jnp.mean(seg, axis=ax, keepdims=True))
                out = jnp.concatenate(segs, axis=ax)
        return out
    return fn


def adaptive_avg_pool1d(x, output_size, name=None):
    return dispatch(_adaptive_pool(x, output_size, 1, "avg", False), (x,), {},
                    name="adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return dispatch(_adaptive_pool(x, output_size, 2, "avg", data_format == "NHWC"),
                    (x,), {}, name="adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return dispatch(_adaptive_pool(x, output_size, 3, "avg", data_format == "NDHWC"),
                    (x,), {}, name="adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return dispatch(_adaptive_pool(x, output_size, 1, "max", False), (x,), {},
                    name="adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return dispatch(_adaptive_pool(x, output_size, 2, "max", False), (x,), {},
                    name="adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return dispatch(_adaptive_pool(x, output_size, 3, "max", False), (x,), {},
                    name="adaptive_max_pool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCL", name=None):
    """reference: nn/functional/pooling.py lp_pool1d → phi lp_pool (funcs/pooling.h
    LPPool): (sum |x|^p)^(1/p) over each window."""
    pw = float(norm_type)

    def fn(v):
        powed = jnp.power(jnp.abs(v), pw)
        pool = _pool(None, kernel_size, stride, padding, 1, jax.lax.add,
                     0.0, data_format == "NLC", ceil_mode, is_avg=False)(powed)
        return jnp.power(pool, 1.0 / pw)
    return dispatch(fn, (x,), {}, name="lp_pool1d")


def _max_unpool(x, indices, kernel_size, stride, padding, output_size, n,
                data_format, op_name):
    """Shared unpool: scatter x into zeros at the flat spatial `indices`
    recorded by max_pool(return_mask=True) (reference: phi unpool kernels)."""
    k = _tuple(kernel_size, n)
    s = _tuple(stride if stride is not None else kernel_size, n)
    p = _tuple(padding, n)
    in_spatial = tuple(int(d) for d in x.shape[2:])
    if output_size is None:
        out_spatial = tuple((in_spatial[i] - 1) * s[i] - 2 * p[i] + k[i]
                            for i in range(n))
    else:
        out_spatial = tuple(int(v) for v in output_size[-n:])

    def fn(v, idx):
        N, C = v.shape[0], v.shape[1]
        flat_out = 1
        for d in out_spatial:
            flat_out *= d
        vflat = v.reshape(N, C, -1)
        iflat = idx.reshape(N, C, -1).astype(jnp.int32)
        zeros = jnp.zeros((N, C, flat_out), v.dtype)
        out = jax.vmap(jax.vmap(lambda z, i, src: z.at[i].set(src)))(
            zeros, iflat, vflat)
        return out.reshape((N, C) + out_spatial)
    return dispatch(fn, (x, indices), {}, name=op_name)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 1,
                       data_format, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 2,
                       data_format, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, output_size, 3,
                       data_format, "max_unpool3d")


def _fractional_bounds(in_sz, out_sz, u, pool_size):
    """Start/end indices per output cell (reference: funcs/pooling.h
    FractionalRationalU/StartIndex/EndIndex)."""
    alpha = in_sz / out_sz
    if pool_size <= 0:
        base = in_sz // out_sz
        u_max1 = (base + 2) / alpha - 1
        u_max2 = (in_sz + 1 - base) / alpha - (out_sz - 1)
        u = u * min(u_max1, u_max2)
    starts, ends = [], []
    for i in range(out_sz):
        st = int((i + u) * alpha) - int(u * alpha)
        if pool_size > 0:
            en = st + pool_size
        else:
            en = int((i + 1 + u) * alpha) - int(u * alpha)
        starts.append(max(0, st))
        ends.append(min(in_sz, en))
    return starts, ends


def _fractional_max_pool(x, output_size, kernel_size, random_u, return_mask, n,
                         op_name):
    from ...core import random as _random
    out_sz = [int(v) for v in
              (output_size if not isinstance(output_size, int)
               else (output_size,) * n)]
    ksz = [0] * n if kernel_size is None else list(_tuple(kernel_size, n))
    if random_u is None:
        key = _random.next_key()
        random_u = float(jax.random.uniform(key, ()))
    u = float(random_u)
    in_spatial = [int(d) for d in x.shape[2:]]
    bounds = [_fractional_bounds(in_spatial[i], out_sz[i], u, ksz[i])
              for i in range(n)]
    kmax = [max(e - s for s, e in zip(*bounds[i])) for i in range(n)]

    def fn(v):
        N, C = v.shape[0], v.shape[1]
        vals = v
        # per dim: gather windows then fold the window axis to the end
        sel_idx = []  # per-dim (out, kmax) gather indices + mask
        for d in range(n):
            starts = np.asarray(bounds[d][0])
            ends = np.asarray(bounds[d][1])
            gather = starts[:, None] + np.arange(kmax[d])[None, :]
            mask = gather < ends[:, None]
            gather = np.minimum(gather, in_spatial[d] - 1)
            sel_idx.append((jnp.asarray(gather), jnp.asarray(mask)))
        # flat index tracking for the mask output
        flat = None
        if return_mask:
            flat = jnp.arange(int(np.prod(in_spatial)), dtype=jnp.int32)
            flat = jnp.broadcast_to(
                flat.reshape((1, 1) + tuple(in_spatial)), v.shape)
        for d in range(n):
            axis = 2 + d  # current dim position (earlier dims already pooled)
            gather, mask = sel_idx[d]
            vals = jnp.take(vals, gather.reshape(-1), axis=axis)
            new_shape = vals.shape[:axis] + (out_sz[d], kmax[d]) + \
                vals.shape[axis + 1:]
            vals = vals.reshape(new_shape)
            mshape = [1] * len(new_shape)
            mshape[axis], mshape[axis + 1] = out_sz[d], kmax[d]
            neg = jnp.where(mask.reshape(mshape), 0.0, -jnp.inf).astype(v.dtype)
            vals = vals + neg
            if return_mask:
                flat = jnp.take(flat, gather.reshape(-1), axis=axis)
                flat = flat.reshape(new_shape)
                am = jnp.argmax(vals, axis=axis + 1, keepdims=True)
                flat = jnp.take_along_axis(flat, am, axis=axis + 1)
                flat = jnp.squeeze(flat, axis=axis + 1)
            vals = jnp.max(vals, axis=axis + 1)
        if return_mask:
            return vals, flat
        return vals

    return dispatch(fn, (x,), {}, name=op_name)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 2, "fractional_max_pool2d")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 3, "fractional_max_pool3d")
