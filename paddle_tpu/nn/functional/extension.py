"""Extension functionals: sequence_mask, temporal_shift, affine_grid,
grid_sample, gather_tree, class_center_sample, sparse_attention.

Reference: python/paddle/nn/functional/{extension,vision,input}.py → phi
kernels (temporal_shift_kernel, affine_grid_kernel, grid_sample_kernel,
gather_tree_kernel, class_center_sample_kernel, sparse_attention GPU kernel).
TPU-native: pure gather/where formulations that XLA fuses; sparse_attention
lowers the CSR pattern to a dense additive mask (TPU has no CSR gather unit —
the flash/splash Pallas kernels in ops/kernels are the perf path, this op is
the API-parity path).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from ...core import random as _random


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """mask[i, j] = j < x[i] (reference: extension.py:56)."""
    from ...core.dtype import convert_dtype
    jdt = convert_dtype(dtype)

    def fn(v):
        ml = maxlen
        if ml is None:
            ml = int(jnp.max(v)) if v.size else 0
        ar = jnp.arange(ml, dtype=v.dtype)
        return (ar < v[..., None]).astype(jdt)
    return dispatch(fn, (x,), {}, name="sequence_mask")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """TSM channel shift (reference: extension.py:247 → phi temporal_shift):
    first c1 channels take t-1, next c1 take t+1, rest pass through."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(
            f"Attr(data_format) should be 'NCHW' or 'NHWC'. Received "
            f"Attr(data_format): {data_format}.")

    def fn(v):
        chan_last = data_format == "NHWC"
        if chan_last:
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v5 = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        fwd = jnp.pad(v5[:, :-1, :c1], ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
        bwd = jnp.pad(v5[:, 1:, c1:c2], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
        out = jnp.concatenate([fwd, bwd, v5[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if chan_last:
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return dispatch(fn, (x,), {}, name="temporal_shift")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D/3D affine sampling grid (reference: vision.py affine_grid)."""
    shape = [int(s) for s in (out_shape.tolist() if isinstance(out_shape, Tensor)
                              else out_shape)]

    def base_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size) if size > 1 else jnp.zeros((1,))
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    def fn(th):
        if len(shape) == 4:
            n, _, h, w = shape
            xs = base_coords(w)
            ys = base_coords(h)
            gx, gy = jnp.meshgrid(xs, ys)  # (h, w)
            ones = jnp.ones_like(gx)
            base = jnp.stack([gx, gy, ones], axis=-1)  # (h, w, 3)
            # theta: (n, 2, 3); grid = base @ theta^T
            return jnp.einsum("hwk,nck->nhwc", base, th.astype(jnp.float32)) \
                .astype(th.dtype)
        n, _, d, h, w = shape
        xs = base_coords(w)
        ys = base_coords(h)
        zs = base_coords(d)
        gz, gy, gx = jnp.meshgrid(zs, ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, gz, ones], axis=-1)  # (d, h, w, 4)
        return jnp.einsum("dhwk,nck->ndhwc", base, th.astype(jnp.float32)) \
            .astype(th.dtype)
    return dispatch(fn, (theta,), {}, name="affine_grid")


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect(coord, size, align_corners):
    if size <= 1:
        return jnp.zeros_like(coord)
    if align_corners:
        span = 2.0 * (size - 1)
        c = jnp.abs(jnp.mod(coord, span))
        return jnp.where(c > size - 1, span - c, c)
    span = 2.0 * size
    c = jnp.mod(coord + 0.5, span)
    c = jnp.abs(c)
    c = jnp.where(c > size, span - c, c) - 0.5
    return jnp.clip(c, 0, size - 1)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x at normalized grid locations (reference: vision.py grid_sample
    → phi grid_sample kernel). Supports 4-D (NCHW + NHW2 grid) and 5-D."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"mode should be 'bilinear' or 'nearest', got {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(
            f"padding_mode should be 'zeros'/'border'/'reflection', got "
            f"{padding_mode}")

    def sample_2d(v, g):
        n, c, h, w = v.shape
        gx = _unnormalize(g[..., 0].astype(jnp.float32), w, align_corners)
        gy = _unnormalize(g[..., 1].astype(jnp.float32), h, align_corners)
        if padding_mode == "border":
            gx = jnp.clip(gx, 0, w - 1)
            gy = jnp.clip(gy, 0, h - 1)
        elif padding_mode == "reflection":
            gx = _reflect(gx, w, align_corners)
            gy = _reflect(gy, h, align_corners)

        def gather(iy, ix):
            iyc = jnp.clip(iy, 0, h - 1)
            ixc = jnp.clip(ix, 0, w - 1)
            # v: (n, c, h, w); indices: (n, oh, ow)
            out = jax.vmap(lambda vb, iyb, ixb: vb[:, iyb, ixb])(v, iyc, ixc)
            if padding_mode == "zeros":
                valid = ((iy >= 0) & (iy <= h - 1) & (ix >= 0) &
                         (ix <= w - 1))[:, None]
                out = jnp.where(valid, out, 0.0)
            return out

        if mode == "nearest":
            return gather(jnp.round(gy).astype(jnp.int32),
                          jnp.round(gx).astype(jnp.int32)).astype(v.dtype)
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx = (gx - x0)[:, None]
        wy = (gy - y0)[:, None]
        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        v00 = gather(y0i, x0i)
        v01 = gather(y0i, x0i + 1)
        v10 = gather(y0i + 1, x0i)
        v11 = gather(y0i + 1, x0i + 1)
        top = v00 * (1 - wx) + v01 * wx
        bot = v10 * (1 - wx) + v11 * wx
        return (top * (1 - wy) + bot * wy).astype(v.dtype)

    def sample_3d(v, g):
        n, c, d, h, w = v.shape
        gx = _unnormalize(g[..., 0].astype(jnp.float32), w, align_corners)
        gy = _unnormalize(g[..., 1].astype(jnp.float32), h, align_corners)
        gz = _unnormalize(g[..., 2].astype(jnp.float32), d, align_corners)
        if padding_mode == "border":
            gx, gy, gz = (jnp.clip(gx, 0, w - 1), jnp.clip(gy, 0, h - 1),
                          jnp.clip(gz, 0, d - 1))
        elif padding_mode == "reflection":
            gx = _reflect(gx, w, align_corners)
            gy = _reflect(gy, h, align_corners)
            gz = _reflect(gz, d, align_corners)

        def gather(iz, iy, ix):
            izc = jnp.clip(iz, 0, d - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            ixc = jnp.clip(ix, 0, w - 1)
            out = jax.vmap(lambda vb, izb, iyb, ixb: vb[:, izb, iyb, ixb])(
                v, izc, iyc, ixc)
            if padding_mode == "zeros":
                valid = ((iz >= 0) & (iz <= d - 1) & (iy >= 0) & (iy <= h - 1) &
                         (ix >= 0) & (ix <= w - 1))[:, None]
                out = jnp.where(valid, out, 0.0)
            return out

        if mode == "nearest":
            return gather(jnp.round(gz).astype(jnp.int32),
                          jnp.round(gy).astype(jnp.int32),
                          jnp.round(gx).astype(jnp.int32)).astype(v.dtype)
        x0, y0, z0 = jnp.floor(gx), jnp.floor(gy), jnp.floor(gz)
        wx, wy, wz = ((gx - x0)[:, None], (gy - y0)[:, None], (gz - z0)[:, None])
        xi, yi, zi = (x0.astype(jnp.int32), y0.astype(jnp.int32),
                      z0.astype(jnp.int32))
        out = 0.0
        for dz, fz in ((0, 1 - wz), (1, wz)):
            for dy, fy in ((0, 1 - wy), (1, wy)):
                for dx, fx in ((0, 1 - wx), (1, wx)):
                    out = out + gather(zi + dz, yi + dy, xi + dx) * fz * fy * fx
        return out.astype(v.dtype)

    def fn(v, g):
        return sample_2d(v, g) if v.ndim == 4 else sample_3d(v, g)
    return dispatch(fn, (x, grid), {}, name="grid_sample")


def gather_tree(ids, parents):
    """Beam-search backtrace (reference: extension.py gather_tree → phi
    gather_tree kernel): walk parent pointers from the last step backwards."""

    def fn(idv, parv):
        # (max_time, batch, beam)
        T = idv.shape[0]

        def step(beam_sel, t):
            # beam_sel: (batch, beam) — beams chosen at step t+1
            par = parv[t]  # (batch, beam)
            sel = jnp.take_along_axis(par, beam_sel, axis=-1)
            out = jnp.take_along_axis(idv[t], beam_sel, axis=-1)
            return sel, out

        init = jnp.broadcast_to(jnp.arange(idv.shape[2], dtype=idv.dtype),
                                idv.shape[1:])
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return outs[::-1]
    return dispatch(fn, (ids, parents), {}, name="gather_tree")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers for partial-FC style training
    (reference: nn/functional/common.py class_center_sample → phi kernel).
    Returns (remapped_label, sampled_class_center). Positive classes always
    kept; negatives uniformly sampled to reach num_samples unique classes."""
    lab = np.asarray(label._value if isinstance(label, Tensor) else label)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = np.sort(pos)
    else:
        key = _random.next_key()
        perm = np.asarray(jax.random.permutation(key, num_classes))
        neg = perm[~np.isin(perm, pos)][: num_samples - len(pos)]
        sampled = np.sort(np.concatenate([pos, neg]))
    remap = np.full((num_classes,), -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    remapped = remap[lab]
    return (Tensor(jnp.asarray(remapped)), Tensor(jnp.asarray(sampled)))


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention over a CSR connectivity pattern (reference:
    nn/functional/sparse_attention.py → GPU-only sparse_attention kernel).
    Lowered to attention with a dense additive mask built from the CSR
    pattern — correct for any pattern; use ops.kernels.flash_attention for the
    TPU perf path."""

    def fn(q, k, v, offs, cols, kpm, am):
        # q/k/v: (B, H, S, D); offs: (B, H, S+1); cols: (B, H, nnz)
        B, H, S, D = q.shape
        scale = 1.0 / np.sqrt(D)
        scores = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
        # dense mask from CSR: row i attends to cols[offs[i]:offs[i+1]]
        nnz = cols.shape[-1]
        ar = jnp.arange(nnz)
        row_of = jnp.sum(ar[None, None, :, None] >=
                         offs[:, :, None, 1:], axis=-1)  # (B,H,nnz)
        allowed = jnp.zeros((B, H, S, S), bool)
        bidx = jnp.arange(B)[:, None, None]
        hidx = jnp.arange(H)[None, :, None]
        allowed = allowed.at[bidx, hidx, row_of, cols].set(True)
        neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
        scores = jnp.where(allowed, scores, neg)
        if kpm is not None:
            scores = jnp.where(kpm[:, None, None, :] != 0, scores, neg)
        if am is not None:
            scores = scores + am
        probs = jax.nn.softmax(scores, axis=-1)
        probs = jnp.where(allowed, probs, 0.0)
        return jnp.einsum("bhst,bhtd->bhsd", probs, v)
    return dispatch(fn, (query, key, value, sparse_csr_offset,
                         sparse_csr_columns, key_padding_mask, attn_mask), {},
                    name="sparse_attention")
