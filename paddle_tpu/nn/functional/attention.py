"""Attention functionals.

Reference: python/paddle/nn/functional/flash_attention.py (flash_attention:358,
scaled_dot_product_attention:1139, flashmask_attention:1299) → FA2 CUDA library.
TPU-native: the public API accepts paddle's [batch, seq, heads, head_dim] layout and
routes to a Pallas flash-attention kernel on TPU (ops/kernels/flash_attention.py);
elsewhere (CPU tests) it uses the exact jnp reference path. Dropout inside attention
uses the global RNG stream.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from ...core import random as _random
from ...core.flags import define_flag, flag_value

define_flag("use_pallas_flash_attention", True,
            "route scaled_dot_product_attention to the Pallas kernel on TPU")


def _sdpa_reference(q, k, v, mask, causal, dropout_p, dropout_key, scale=None):
    """Exact attention in [B, S, H, D] layout; fp32 softmax accumulation."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    # [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # grouped-query: broadcast kv heads
    if kt.shape[1] != qt.shape[1]:
        rep = qt.shape[1] // kt.shape[1]
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), vt)
    return jnp.swapaxes(out, 1, 2)  # back to [B, S, H, D]


def _use_pallas(q_val):
    if not flag_value("use_pallas_flash_attention"):
        return False
    try:
        dev = next(iter(q_val.devices()))
        return dev.platform in ("tpu", "axon")
    except Exception:
        # tracer (jit/checkpoint/vmap): no device on the value — decide from
        # the backend. Returning False here would silently downgrade remat'd
        # attention to the O(S^2)-memory einsum path.
        return jax.default_backend() in ("tpu", "axon")


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """paddle.nn.functional.scaled_dot_product_attention — [B, S, H, D] layout."""
    if not training:
        dropout_p = 0.0
    dropout_key = _random.next_key() if dropout_p > 0.0 else None

    q_val = query._value if isinstance(query, Tensor) else query
    k_val = key._value if isinstance(key, Tensor) else key
    # Pallas kernel masks top-left aligned (rows >= cols); the reference
    # semantics are bottom-right aligned (tril k=sk-sq), which only coincide
    # when sq == sk — route unequal lengths (e.g. kv-cache decode) to the
    # XLA path.
    if (_use_pallas(q_val) and attn_mask is None and dropout_p < 1.0
            and (not is_causal or q_val.shape[1] == k_val.shape[1])):
        from ...ops.kernels.flash_attention import (flash_attention_fwd,
                                                    seed_carrier)
        if dropout_p > 0.0:
            # dropout runs INSIDE the kernel (position-hashed mask, same in
            # fwd and bwd) — without this, every dropout-using transformer
            # (bert/vit) would fall off the flash path onto O(S^2) einsum.
            # The seed crosses the DISPATCH boundary as int32 so AMP's
            # cast-all-float-leaves autocast can't corrupt the bit pattern
            # (the op name is AMP white-listed — q/k/v still downcast).
            seed_i = jax.lax.bitcast_convert_type(seed_carrier(dropout_key),
                                                  jnp.int32)

            def fn(q, k, v, si):
                sf = jax.lax.bitcast_convert_type(si, jnp.float32)
                return flash_attention_fwd(q, k, v, causal=is_causal,
                                           dropout_p=dropout_p, seed_f=sf)
            return dispatch(fn, (query, key, value, seed_i), {},
                            name="flash_attention_dropout")

        def fn(q, k, v):
            return flash_attention_fwd(q, k, v, causal=is_causal)
        return dispatch(fn, (query, key, value), {}, name="flash_attention")

    def fn(q, k, v, *m):
        return _sdpa_reference(q, k, v, m[0] if m else None, is_causal, dropout_p,
                               dropout_key)
    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    return dispatch(fn, args, {}, name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity wrapper."""
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal,
                                       training)
    return (out, None) if return_softmax else (out, None)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                        max_seqlen_k, scale=None, dropout=0.0, causal=False,
                        return_softmax=False, training=True, name=None):
    """Varlen flash attention: ragged batches packed as one sequence with cu_seqlens.

    Implemented by segment-masking the packed sequence (TPU-friendly static shapes;
    the reference calls FA2's varlen CUDA path)."""
    def fn(q, k, v, cq, ck):
        # q: [total_q, H, D]
        total_q = q.shape[0]
        total_k = k.shape[0]
        seg_q = jnp.searchsorted(cq, jnp.arange(total_q), side="right") - 1
        seg_k = jnp.searchsorted(ck, jnp.arange(total_k), side="right") - 1
        d = q.shape[-1]
        s = scale if scale is not None else 1.0 / (d ** 0.5)
        logits = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) * s
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(total_q) - jnp.take(cq, seg_q)
            pos_k = jnp.arange(total_k) - jnp.take(ck, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.where(mask[None], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(mask[None], probs, 0.0)
        return jnp.einsum("hqk,khd->qhd", probs.astype(v.dtype), v)
    out = dispatch(fn, (query, key, value, cu_seqlens_q, cu_seqlens_k), {},
                   name="flash_attn_unpadded")
    return out, None


def flashmask_attention(query, key, value, startend_row_indices=None, dropout=0.0,
                        causal=False, training=True, name=None):
    """Column-sparse masked attention (reference: flash_attention.py:1299).

    startend_row_indices: [B, KVH, S_k, {1,2,4}] — per-key-column row bounds that mask
    out rows of the attention matrix. We materialize the boolean mask from the bounds
    (jnp path); the Pallas kernel path can consume the same bounds blockwise.
    """
    def fn2(q, k, v, *ri):
        b, sq, h, d = q.shape
        sk = k.shape[1]
        qi = jnp.arange(sq)[:, None]   # [Sq,1]
        ki = jnp.arange(sk)[None, :]   # [1,Sk]
        base = (qi >= ki) if causal else jnp.ones((sq, sk), bool)
        allow = jnp.broadcast_to(base, (b, 1, sq, sk))
        if ri:
            r = ri[0].astype(jnp.int32)  # [B, KVH, Sk, n]
            n = r.shape[-1]
            kvh = r.shape[1]
            rT = jnp.swapaxes(r, 2, 3)  # [B, KVH, n, Sk]
            q_idx = qi[None, None]      # [1,1,Sq,1]
            if causal:
                if n == 1:  # LT start: mask rows >= start (except diagonal region)
                    start = rT[:, :, 0][:, :, None, :]  # [B,KVH,1,Sk]
                    m = q_idx < start
                else:       # n == 2: LT start/end band
                    start = rT[:, :, 0][:, :, None, :]
                    end = rT[:, :, 1][:, :, None, :]
                    m = (q_idx < start) | (q_idx >= end)
                allow = allow & m
            else:
                if n == 2:  # LT start + UT end
                    lts = rT[:, :, 0][:, :, None, :]
                    ute = rT[:, :, 1][:, :, None, :]
                    m = (q_idx < lts) & (q_idx >= ute)
                else:       # n == 4: LT start/end + UT start/end
                    lts = rT[:, :, 0][:, :, None, :]
                    lte = rT[:, :, 1][:, :, None, :]
                    uts = rT[:, :, 2][:, :, None, :]
                    ute = rT[:, :, 3][:, :, None, :]
                    m = ((q_idx < lts) | (q_idx >= lte)) & \
                        ((q_idx >= ute) | (q_idx < uts))
                allow = allow & m
            if kvh != h and kvh == 1:
                pass  # broadcast over heads
        scale = 1.0 / (d ** 0.5)
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        if kt.shape[1] != qt.shape[1]:
            rep = qt.shape[1] // kt.shape[1]
            kt = jnp.repeat(kt, rep, axis=1)
            vt = jnp.repeat(vt, rep, axis=1)
            if ri and allow.shape[1] not in (1, qt.shape[1]):
                allow = jnp.repeat(allow, rep, axis=1)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * scale
        logits = jnp.where(allow, logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(jnp.isnan(probs), 0.0, probs)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), vt)
        return jnp.swapaxes(out, 1, 2)
    args = (query, key, value) + ((startend_row_indices,)
                                  if startend_row_indices is not None else ())
    return dispatch(fn2, args, {}, name="flashmask_attention")


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    raise NotImplementedError(
        "sparse_attention: use flashmask_attention or scaled_dot_product_attention")


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, rng_name="", training=True,
                         name=None):
    """Packed-QKV flash attention (reference: flash_attention.py
    flash_attn_qkvpacked): qkv [B, S, G + 2, Hk, D] — the first G slots along
    axis 2 are Q head-groups, the LAST two are K and V (the FA2 packing).
    Flattened q head j = g*Hk + h attends kv head j // G, which is exactly the
    repeat-broadcast rule in _sdpa_reference."""
    num_g = qkv.shape[2] - 2
    q = qkv[:, :, :-2]
    k = qkv[:, :, -2]
    v = qkv[:, :, -1]
    B, S = q.shape[0], q.shape[1]
    q = q.reshape([B, S, num_g * qkv.shape[3], qkv.shape[4]])
    return flash_attention(q, k, v, dropout, causal, return_softmax,
                           fixed_seed_offset, rng_name, training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale=None, dropout=0.0,
                                causal=False, return_softmax=False,
                                fixed_seed_offset=None, rng_name="",
                                varlen_padded=True, training=True, name=None):
    """Varlen packed-QKV flash attention (reference: flash_attention.py
    flash_attn_varlen_qkvpacked): qkv [total, G + 2, Hk, D] — Q groups first,
    K and V in the last two slots."""
    num_g = qkv.shape[1] - 2
    q = qkv[:, :-2].reshape([qkv.shape[0], num_g * qkv.shape[2], qkv.shape[3]])
    k = qkv[:, -2]
    v = qkv[:, -1]
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale, dropout,
                               causal, return_softmax, training)
