"""Parameter initializers (paddle.nn.initializer analog).

Reference: python/paddle/nn/initializer/ — Constant/Normal/Uniform/Xavier/KaimingMSRA/
TruncatedNormal/Assign. Each initializer is a callable (shape, dtype) -> jax array,
drawing from the global RNG stream (core/random.py).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dtype as dtypes
from ...core import random as _random
from ...core.tensor import Tensor


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        compute = jnp.float32 if dtype == dtypes.bfloat16 else dtype
        x = self.mean + self.std * jax.random.normal(_random.next_key(), tuple(shape),
                                                     compute)
        return x.astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        compute = jnp.float32 if dtype == dtypes.bfloat16 else dtype
        x = jax.random.truncated_normal(_random.next_key(), self.a, self.b,
                                        tuple(shape), compute)
        return (self.mean + self.std * x).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        compute = jnp.float32 if dtype == dtypes.bfloat16 else dtype
        x = jax.random.uniform(_random.next_key(), tuple(shape), compute,
                               self.low, self.high)
        return x.astype(dtype)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = \
            fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fi)
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = \
            fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) \
            if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(v, dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign initializer shape {arr.shape} != param shape {tuple(shape)}"
        return arr


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out_c, in_c, *spatial = shape
        w = np.zeros(tuple(shape), np.float32)
        center = tuple(s // 2 for s in spatial)
        for g in range(self.groups):
            for i in range(min(out_c // self.groups, in_c)):
                w[(g * (out_c // self.groups) + i, i) + center] = 1.0
        return jnp.asarray(w, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(_random.next_key(), (max(rows, cols), min(rows, cols)),
                                 jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(tuple(shape)).astype(dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


def set_global_initializer(weight_init, bias_init=None):
    from .. import layer_base
    layer_base._GLOBAL_WEIGHT_INIT = weight_init
    layer_base._GLOBAL_BIAS_INIT = bias_init


class Bilinear(Initializer):
    """Bilinear upsampling kernel init for transposed convs (reference:
    nn/initializer/Bilinear — used to initialize deconv weights so the layer
    starts as bilinear interpolation)."""

    def __call__(self, shape, dtype):
        # shape: (C_in, C_out/g, kh, kw) for conv-transpose or (out, in, kh, kw)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        kh, kw = shape[2], shape[3]
        # reference derives ONE factor from shape[3] for both axes
        f = int(np.ceil(kw / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        yy, xx = np.meshgrid(np.arange(kh), np.arange(kw), indexing="ij")
        filt = (1 - np.abs(yy / f - c)) * (1 - np.abs(xx / f - c))
        w = np.broadcast_to(filt, tuple(shape)).astype(np.float32)
        return jnp.asarray(w, dtype)
