"""Postmortem black box — bounded debug bundles for serving incidents.

When a replica crashes, wedges, or starts burning an SLO budget, the
question five minutes later is always the same: *what was it doing?*
The live observability stack (flight recorder, metrics store, alert
log, engine stats) holds the answer — but only until the process exits
or the ring wraps. This module is the flight-data-recorder dump: one
bounded, schema-tagged JSON file capturing the tails of every in-memory
diagnostic surface at the moment of the incident:

* the flight recorder's StepRecord **ring tail** and its worst
  ``explain_tail`` gaps (with their cause verdicts and trace ids),
* the metrics store's **series tails** and the full **alert log**,
* an **engine snapshot**: config, cumulative stats, paged-pool / host
  KV-tier / ship-store occupancy,
* the server's health/restart state and the fault injector's fired
  record (chaos runs are self-describing).

Triggers (armed via ``AsyncLLMServer(black_box=...)``): crash→restart,
the watchdog's hang verdict, and each metrics-store alert RAISE —
**edge-triggered** (one bundle per alert instance, not per evaluation)
and **deduped** (a crash loop produces one bundle per
``dedup_window_s``, not one per restart). Manual dumps via
``server.dump_debug_bundle(path)`` / ``router.dump_debug_bundle(dir)``
skip both gates. Every bundle is **byte-bounded**: the dump shrinks its
tails until the serialized JSON fits ``max_bytes``, so an armed black
box can never fill a disk however long the incident runs.

Read a bundle back with ``python -m paddle_tpu.profiler.bundle <path>``.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["BlackBox", "collect_bundle", "write_bundle",
           "BUNDLE_SCHEMA", "TRIGGER_REASONS"]

#: the schema tag every bundle carries — the pretty-printer (and any
#: downstream tooling) validates it before trusting field shapes
BUNDLE_SCHEMA = "paddle_tpu.debug_bundle/v1"

#: every reason an automatic or manual dump may carry
TRIGGER_REASONS = ("crash", "hang", "burn_alert", "manual")


def _json_safe(obj, depth=0):
    """Coerce ``obj`` into JSON-encodable primitives: numpy scalars to
    Python numbers, small arrays to lists, anything else to ``str``.
    Depth-bounded — a cyclic or pathological structure degrades to its
    repr instead of recursing forever."""
    if depth > 6:
        return str(obj)
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (bytes, bytearray)):
        return obj.hex()
    if isinstance(obj, dict):
        return {str(k): _json_safe(v, depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        seq = list(obj)
        if len(seq) > 256:
            seq = seq[:256]
        return [_json_safe(v, depth + 1) for v in seq]
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return _json_safe(item(), depth + 1)  # numpy scalar
        except (TypeError, ValueError):
            pass
    tolist = getattr(obj, "tolist", None)
    if callable(tolist):
        try:
            return _json_safe(tolist(), depth + 1)  # small numpy array
        except (TypeError, ValueError):
            pass
    return str(obj)


def _engine_snapshot(engine):
    """Config + occupancy facts of one engine, read defensively (every
    field is a plain attribute read — safe from any thread, even while
    the engine thread is wedged inside a step)."""
    if engine is None:
        return None
    snap = {}
    for attr in ("cache_impl", "scheduler", "B", "capacity", "block_size",
                 "n_blocks", "speculative_k", "readout_stride",
                 "kv_cache_dtype", "kv_host_swap"):
        v = getattr(engine, attr, None)
        if v is not None:
            snap[attr] = _json_safe(v)
    stats = getattr(engine, "stats", None)
    if isinstance(stats, dict):
        snap["stats"] = {k: _json_safe(v) for k, v in stats.items()
                         if isinstance(v, (int, float))}
    free = getattr(engine, "_free_blocks", None)
    if free is not None:
        snap["pool"] = {
            "free_blocks": len(free),
            "cached_blocks": len(getattr(engine, "_lru", ())),
            "spill_blocks": len(getattr(engine, "_spill", ())),
            "spill_bytes": _json_safe(getattr(engine, "_spill_bytes", 0)),
            "swap_store_rids": sorted(
                _json_safe(r)
                for r in getattr(engine, "_swap_store", {}) or ()),
            "export_store_rids": sorted(
                _json_safe(r)
                for r in getattr(engine, "_export_store", {}) or ()),
            "kv_pool_bytes": _json_safe(
                getattr(engine, "_kv_nbytes", None)),
        }
    slots = getattr(engine, "slots", None)
    if slots is not None:
        snap["resident_rids"] = [_json_safe(s.req.request_id)
                                 for s in slots if s is not None]
        snap["waiting"] = len(getattr(engine, "waiting", ()))
    return snap


def collect_bundle(server=None, engine=None, recorder=None,
                   metrics_store=None, reason="manual", detail=None,
                   ring_tail=64, series_tail=32, tail_top=16):
    """Assemble one debug-bundle dict from whatever diagnostic surfaces
    exist. Pass a ``server`` and the engine / recorder / store are
    taken from it; any surface may be absent (its section is None).
    Every read is lock-cheap and defensive — collection must work
    while the serve loop is crashed or wedged."""
    if reason not in TRIGGER_REASONS:
        raise ValueError(f"unknown bundle reason {reason!r} "
                         f"(one of {TRIGGER_REASONS})")
    if server is not None:
        engine = engine or server.engine
        recorder = recorder or server.flight_recorder
        metrics_store = metrics_store or server.metrics_store
    bundle = {
        "schema": BUNDLE_SCHEMA,
        "reason": reason,
        "detail": detail,
        "pid": os.getpid(),
        "monotonic_t": round(time.monotonic(), 6),
        "perf_t": round(time.perf_counter(), 6),
    }
    if server is not None:
        try:
            health = server.health()
        except Exception:
            health = None
        bundle["server"] = {
            "replica": server.replica,
            "health": _json_safe(health),
            "restarts": getattr(server, "restarts", 0),
            "outstanding": server.num_outstanding(),
            "queue_depth": len(server._queue),
        }
        fi = getattr(server, "fault_injector", None)
        if fi is not None:
            bundle["faults"] = _json_safe(
                fi.snapshot() if hasattr(fi, "snapshot")
                else list(fi.fired))
    bundle["engine"] = _engine_snapshot(engine)
    if recorder is not None:
        try:
            tail = recorder.explain_tail(0.0, top=tail_top)
        except Exception:
            tail = []
        bundle["flight_recorder"] = {
            "snapshot": _json_safe(recorder.snapshot(tail=tail)),
            "ring_tail": [_json_safe(r.to_dict())
                          for r in recorder.records()[-ring_tail:]],
            "explain_tail": _json_safe(tail),
        }
    else:
        bundle["flight_recorder"] = None
    if metrics_store is not None:
        bundle["metrics"] = _json_safe(
            metrics_store.snapshot(max_samples=series_tail))
    else:
        bundle["metrics"] = None
    return bundle


def _shrink(bundle):
    """Halve the bundle's variable-size tails in place; returns False
    once nothing shrinkable remains (the caller then drops sections)."""
    shrunk = False
    fr = bundle.get("flight_recorder")
    if isinstance(fr, dict):
        for key in ("ring_tail", "explain_tail"):
            seq = fr.get(key)
            if isinstance(seq, list) and len(seq) > 1:
                fr[key] = seq[-(len(seq) // 2):]
                shrunk = True
    ms = bundle.get("metrics")
    if isinstance(ms, dict):
        for s in ms.get("series", ()):
            tail = s.get("tail")
            if isinstance(tail, list) and len(tail) > 1:
                s["tail"] = tail[-(len(tail) // 2):]
                shrunk = True
    return shrunk


def write_bundle(bundle, path, max_bytes=262144):
    """Serialize ``bundle`` to ``path``, shrinking its tails until the
    JSON fits ``max_bytes`` (sorted keys — byte-identical bundles for
    identical state). Returns ``path``."""
    data = json.dumps(bundle, sort_keys=True, indent=1)
    while len(data) > max_bytes:
        if not _shrink(bundle):
            # last resort: drop the bulky sections outright, keep the
            # header + server/engine state, and say so
            bundle["flight_recorder"] = None
            bundle["metrics"] = None
            bundle["truncated"] = True
            data = json.dumps(bundle, sort_keys=True, indent=1)
            break
        bundle["truncated"] = True
        data = json.dumps(bundle, sort_keys=True, indent=1)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(data)
    return path


class BlackBox:
    """The armed automatic dumper: dedup + rotation around
    :func:`collect_bundle`/:func:`write_bundle`.

    * **dedup** — at most one bundle per ``(reason)`` per
      ``dedup_window_s`` (a crash loop or a flapping alert produces a
      bounded trickle, not a flood); the window is per-reason so a hang
      verdict still dumps while a crash window is open.
    * **rotation** — at most ``max_bundles`` files in ``out_dir``;
      oldest (lowest sequence number) deleted first.
    * **bounds** — every file obeys ``max_bytes`` via
      :func:`write_bundle`.

    Thread-safe: the engine thread (crash), the watchdog thread (hang)
    and the serve loop (alert edges) may all dump concurrently."""

    def __init__(self, out_dir="debug_bundles", max_bytes=262144,
                 max_bundles=8, dedup_window_s=30.0, ring_tail=64,
                 series_tail=32, tail_top=16):
        self.out_dir = str(out_dir)
        self.max_bytes = int(max_bytes)
        self.max_bundles = int(max_bundles)
        self.dedup_window_s = float(dedup_window_s)
        self.ring_tail = int(ring_tail)
        self.series_tail = int(series_tail)
        self.tail_top = int(tail_top)
        self._lock = threading.Lock()
        self._last_dump: dict[str, float] = {}   # reason -> monotonic t
        self._seq = 0
        #: every path this instance wrote, newest last (the test-side
        #: record, and the rotation order)
        self.dumped: list[str] = []

    def dump(self, reason, server=None, engine=None, recorder=None,
             metrics_store=None, detail=None, path=None):
        """Collect + write one bundle. Returns the written path, or
        None when the per-reason dedup window suppressed the dump.
        ``path=None`` writes ``bundle_<seq>_<reason>.json`` under
        ``out_dir`` and rotates; an explicit path skips rotation."""
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(reason)
            if path is None and last is not None \
                    and now - last < self.dedup_window_s:
                return None
            self._last_dump[reason] = now
            self._seq += 1
            seq = self._seq
        bundle = collect_bundle(
            server=server, engine=engine, recorder=recorder,
            metrics_store=metrics_store, reason=reason, detail=detail,
            ring_tail=self.ring_tail, series_tail=self.series_tail,
            tail_top=self.tail_top)
        bundle["seq"] = seq
        if path is None:
            path = os.path.join(self.out_dir,
                                f"bundle_{seq:04d}_{reason}.json")
            rotate = True
        else:
            rotate = False
        out = write_bundle(bundle, path, max_bytes=self.max_bytes)
        with self._lock:
            self.dumped.append(out)
            if rotate:
                mine = [p for p in self.dumped
                        if os.path.dirname(p) == self.out_dir]
                while len(mine) > self.max_bundles:
                    victim = mine.pop(0)
                    self.dumped.remove(victim)
                    try:
                        os.remove(victim)
                    except OSError:
                        pass
        return out
