"""Throughput/step timer (reference: python/paddle/profiler/timer.py —
benchmark() singleton with ips/step-time summaries, used by hapi and fleet)."""
from __future__ import annotations

import time


class _Stat:
    def __init__(self):
        self.reset()

    def reset(self):
        self.count = 0
        self.total = 0.0
        self.minimum = float("inf")
        self.maximum = 0.0

    def update(self, v):
        self.count += 1
        self.total += v
        self.minimum = min(self.minimum, v)
        self.maximum = max(self.maximum, v)

    @property
    def avg(self):
        return self.total / self.count if self.count else 0.0


class Benchmark:
    def __init__(self):
        self.reset()

    def reset(self):
        self._last = None
        self.step_time = _Stat()
        self.ips = _Stat()
        self._samples = 0

    def begin(self):
        self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self.step_time.update(dt)
            if num_samples:
                self.ips.update(num_samples / dt)
        self._last = now

    def end(self):
        self._last = None

    def step_info(self, unit=None):
        msg = (f"avg_step_time: {self.step_time.avg * 1e3:.2f} ms "
               f"(min {self.step_time.minimum * 1e3:.2f}, "
               f"max {self.step_time.maximum * 1e3:.2f})")
        if self.ips.count:
            u = unit or "samples"
            msg += f", ips: {self.ips.avg:.2f} {u}/s"
        return msg


_BENCH = Benchmark()


def benchmark() -> Benchmark:
    return _BENCH
