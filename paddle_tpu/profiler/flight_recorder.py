"""Engine flight recorder — per-step ``StepRecord``s + per-request trace
timelines, joined by step id. The causal layer the aggregate serving
telemetry (``serving_telemetry.py``) cannot provide: a p99 inter-token
gap in a histogram looks identical whether it came from an interfering
prefill chunk, a pool-pressure preemption, a pipeline bubble, or a host
sync stall. The recorder answers "why was THIS token slow?".

Three pieces:

* **StepRecord ring** — a fixed-size ring buffer holding one record per
  engine step: scheduler kind, per-slot grants (prefill chunk vs decode
  token), token-budget utilization, queue depth, KV-pool free blocks,
  pipeline depth in flight, preemption events, and the
  admit/schedule/dispatch/sync/emit wall splits. The ring is
  pre-allocated; recording a step is one index assignment, so recorder
  overhead is bounded (and the whole recorder is disableable —
  ``enabled=False`` short-circuits every hook).
* **per-request span timelines** — queued → admitted → prefill chunks →
  first token → per-token gaps → finish reason, each span stamped with
  the step id that produced it, so request time joins back to engine
  state. Per-token cost is one append of a small tuple (the record
  itself) — no other allocation.
* **exports** — :meth:`FlightRecorder.export_chrome_trace` writes a
  chrome://tracing JSON with one lane per request plus an engine-step
  lane (same ``traceEvents``/µs conventions as ``Profiler._export_chrome``,
  so traces open in Perfetto and ``merge_profile`` merges them across
  ranks), and :meth:`FlightRecorder.explain_tail` joins the worst
  inter-token gaps to their causal StepRecord and names the dominant
  cause (interfering prefill / preemption / host sync / idle bubble).

Reference analog: the reference debugs its serving stack with
paddle.profiler timelines; vLLM/Sarathi-style continuous batching is
debugged in production with exactly this per-step/per-request trace
join (PAPERS.md: Sarathi-Serve's stall taxonomy is per-step).
"""
from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time

__all__ = ["StepRecord", "FlightRecorder", "TAIL_CAUSES",
           "REQUEST_EVENT_KINDS", "COUNTER_TRACKS", "FLOW_EVENT_NAME"]

#: the cause labels explain_tail may assign, in priority order.
#: "restart_recovery" outranks everything: the gap spans a supervised
#: engine restart ("crashed" → "resumed" spans in the request timeline),
#: so the step facts explain the resumed side only, not the gap.
#: "batched_readout" refines host_sync for AMORTIZED readouts: the
#: gap's causal step drained a multi-row token burst in one sync
#: (multi-step readout_stride, a legacy horizon scan, or speculative
#: verify windows — StepRecord.readout_stride carries the row count
#: for all three), so a sync-dominated step is the amortization
#: boundary working as designed — tune the stride/horizon, not the
#: host — rather than a host-sync pathology.
#: The preemption cause is SPLIT by the host KV tier's involvement:
#: "preempt_swap" — the gap's causal step preempted slots whose KV
#: moved through the host tier (swap-out at the preemption, or a
#: swap-in restore at the re-admission): the stall is two overlapped
#: copies, already the cheap path — grow the pool or the spill budget
#: if it still hurts. "preempt_reprefill" — the step preempted with NO
#: tier traffic: the evicted KV was recomputed from scratch, the
#: expensive shape tiering exists to remove (kv_host_swap off, or the
#: entry was invalidated).
#: "adapter_swap" sits between preemption and interfering_prefill: the
#: gap's causal step swapped an adapter into the device cache (host
#: upload riding the admission path) — a multi-tenant working set
#: larger than the adapter cache, not a scheduling pathology.
#: "draft_rejected" names a speculative stall: the gap's causal step
#: carried verify grants whose drafts mostly ROLLED BACK (rejected >
#: accepted at readout), so the wall went to verifying tokens that
#: never committed — an acceptance problem (workload/draft mismatch;
#: the adaptive-k EWMA should be shrinking the window), not the
#: host-sync or batched-readout pathology it would otherwise file as.
#: "kv_ship" sits between adapter_swap and interfering_prefill: the
#: gap's causal step moved cross-replica ship traffic (a migrated
#: request's imported KV scattering in with its stitch grant, or a
#: finish-site export staging out) — disaggregation transfer cost, not
#: the prefill interference the mixed step would otherwise file as.
TAIL_CAUSES = ("restart_recovery", "preempt_swap", "preempt_reprefill",
               "adapter_swap", "kv_ship",
               "interfering_prefill", "draft_rejected", "batched_readout",
               "host_sync", "idle_bubble", "dispatch", "unrecorded")

#: every request-timeline event KIND the tree may record (the literal
#: second argument of :meth:`FlightRecorder.req_event`, plus the
#: "token" events :meth:`FlightRecorder.on_token` appends). STRICT
#: schema, like the telemetry names and alert kinds: the PTL008
#: analysis pass (``paddle_tpu.analysis.trace_names``) checks every
#: ``req_event`` call site's kind literal against this tuple, so a
#: typo'd span name fails lint instead of silently opening a phantom
#: lane in the chrome export.
REQUEST_EVENT_KINDS = (
    "queued",          # server admission-queue entry (restarts timeline)
    "routed",          # the ReplicaRouter's placement record
    "admitted",        # engine slot admission
    "prefill",         # one prefill chunk (value = token count)
    "cached_prefix",   # prompt tokens served from the prefix cache
    "token",           # one emitted token (value = inter-token gap)
    "kv_shipped_in",   # cross-replica shipped KV restored into a slot
    "kv_stitch",       # the shipped restore's stitch wall (value = s)
    "swapped_in",      # host-tier preemption swap restored into a slot
    "crashed",         # supervised serving loop crashed under this req
    "resumed",         # supervised restart re-admitted this request
    "finish",          # terminal (value = finish reason)
)

#: the Perfetto counter tracks ("ph":"C") the chrome export emits —
#: one line chart per name under the request lanes. PTL008 checks
#: counter-event name literals against this tuple.
COUNTER_TRACKS = ("queue_depth", "token_budget_utilization",
                  "kv_pool_occupancy", "spec_acceptance_rate")

#: the name every cross-replica Perfetto flow event ("ph":"s"/"f")
#: carries — ``ReplicaRouter.export_merged_trace`` links a request's
#: per-hop lanes with s→f pairs under this one name (flow events match
#: on (name, cat, id), so the name IS schema).
FLOW_EVENT_NAME = "trace_flow"


@dataclasses.dataclass
class StepRecord:
    """One engine step's facts, captured at dispatch and completed at
    readout. ``grants`` is a tuple of ``(slot, request_id, kind,
    n_tokens)`` with kind ``"prefill"`` or ``"decode"`` — the per-slot
    work this step's single dispatch carried."""
    step_id: int
    t_begin: float                     # perf_counter at step_begin entry
    scheduler: str                     # "legacy" | "fused"
    kind: str                          # "decode" | "mixed" | "spec" | "drain"
    grants: tuple                      # ((slot, rid, kind, n_tokens), ...)
    tokens_scheduled: int              # sum of grant n_tokens
    token_budget: int                  # per-step token capacity
    queue_depth: int                   # engine.waiting after admission
    free_blocks: int | None            # paged pool free blocks (None: dense)
    total_blocks: int | None
    pipeline_inflight: int             # dispatches in flight incl. this one
    preemptions: tuple                 # request ids preempted/pool-retired
    admit_s: float                     # wall splits measured by the engine
    schedule_s: float
    dispatch_s: float
    t_finish: float = 0.0              # 0.0 until step_finish completes it
    sync_s: float = 0.0
    emit_s: float = 0.0
    finished: tuple = ()               # request ids retired at readout
    #: prompt tokens this step's admissions served straight from the
    #: prefix cache (None: engine has no prefix cache) — 0 on a step
    #: that admitted cold prompts is the COLD-MISS signal explain_tail
    #: surfaces when such a step stalls a token
    prefix_hit_tokens: int | None = None
    cached_blocks: int | None = None   # LRU cached-pool size at dispatch
    #: token rows per slot this dispatch may drain in ONE readout sync
    #: (the multi-step decode stride; legacy horizon scans and spec
    #: verify windows report their row count here too). 1 = the
    #: classic one-token-per-slot step.
    readout_stride: int = 1
    #: per-slot TENANT ids of this dispatch: ((slot, adapter_id), ...)
    #: for every resident non-base slot — empty on a single-tenant step
    adapter_slots: tuple = ()
    #: adapter device-cache swap-ins that rode this step's admission
    #: (host factor upload) — the explain_tail "adapter_swap" signal
    adapter_swaps: int = 0
    #: speculative verify accounting, completed at readout: drafts this
    #: step committed vs drafts it rolled back (0/0 on non-spec steps).
    #: The per-slot verify grants themselves ride ``grants`` with kind
    #: "verify" and report their window rows through readout_stride.
    spec_accepted: int = 0
    spec_rejected: int = 0
    #: quantized-KV capacity facts (None on dense engines): total pool
    #: bytes (payload + per-block quantization scales) and the pool
    #: storage dtype ("bf16"/"float32" unquantized, "int8"/"int4" under
    #: kv_cache_dtype) — what joins a preemption-churn tail back to
    #: "the pool was simply small for this dtype"
    kv_pool_bytes: int | None = None
    kv_cache_dtype: str | None = None
    #: host KV tier PREEMPTION-SWAP traffic THIS step moved (None on
    #: dense engines; 0 with the tier off): swap-in restores at the
    #: step's scheduling, swap-outs at its preemptions — the exclusive
    #: signal splitting the preemption tail cause into preempt_swap vs
    #: preempt_reprefill (spill/promote traffic deliberately books on
    #: its own counters so an unrelated eviction on a preemption step
    #: cannot fake the cheap path) — plus the host spill store's block
    #: count at dispatch
    kv_swap_in_bytes: int | None = None
    kv_swap_out_bytes: int | None = None
    kv_host_spill_blocks: int | None = None
    #: cross-replica ship traffic THIS step moved (disaggregated
    #: serving: staged-entry import restores / finish-site exports +
    #: pull-on-miss prefix blocks) — separate from the swap bytes so
    #: the preemption classifier's signal stays exclusive; the
    #: explain_tail "kv_ship" cause reads these
    kv_ship_in_bytes: int | None = None
    kv_ship_out_bytes: int | None = None

    @property
    def budget_utilization(self):
        """tokens_scheduled / token_budget. MAY exceed 1.0: the fused
        scheduler never throttles decode tokens or the oldest ramp's
        progress-guarantee token, so a throttled ``max_step_tokens``
        below the live decode count over-grants — a >1 reading IS the
        signal that the budget is too small to bound interference."""
        return self.tokens_scheduled / self.token_budget \
            if self.token_budget else 0.0

    @property
    def prefill_tokens(self):
        # "embed" grants are prefill-only work and interfere with decode
        # latency exactly like generation ramp-in chunks
        return sum(n for _, _, kind, n in self.grants
                   if kind in ("prefill", "embed"))

    @property
    def decode_slots(self):
        # "verify" grants are decode-side work (a speculative slot's
        # committed token + drafts ride one grant)
        return sum(1 for _, _, kind, _ in self.grants
                   if kind in ("decode", "verify"))

    @property
    def wall_s(self):
        return max(self.t_finish - self.t_begin, 0.0) \
            if self.t_finish else self.dispatch_s

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["grants"] = [list(g) for g in self.grants]
        d["preemptions"] = list(self.preemptions)
        d["finished"] = list(self.finished)
        d["adapter_slots"] = [list(a) for a in self.adapter_slots]
        d["budget_utilization"] = round(self.budget_utilization, 4)
        d["prefill_tokens"] = self.prefill_tokens
        return d


#: one timeline event: (kind, t, step_id, value) — value is the token's
#: inter-token gap ("token"), the chunk's token count ("prefill"), or the
#: finish reason ("finish"); None otherwise. A plain tuple keeps the
#: per-token append allocation-minimal.
_EVENT_FIELDS = ("kind", "t", "step_id", "value")


class _RequestTrace:
    __slots__ = ("request_id", "events", "last_token_t", "prefix_hit",
                 "routing", "trace_ctx")

    def __init__(self, request_id):
        self.request_id = request_id
        self.events = []
        self.last_token_t = None
        #: cached-prefix tokens this request's admission served from the
        #: prefix cache (None until a "cached_prefix" event lands) — what
        #: explain_tail joins prefill-grant interference back to
        self.prefix_hit = None
        #: the placement metadata a "routed" event carried (the replica
        #: router's decision) — explain_tail surfaces it on tail entries
        self.routing = None
        #: the distributed trace context this timeline ran under (dict:
        #: trace_id/hop/parent/via) — the cross-replica join key the
        #: merged-trace stitcher and the router's fleet explain_tail
        #: group per-hop timelines by
        self.trace_ctx = None

    def to_dict(self):
        d = {"request_id": self.request_id,
             "events": [dict(zip(_EVENT_FIELDS, e))
                        for e in self.events]}
        if self.trace_ctx is not None:
            d["trace_ctx"] = dict(self.trace_ctx)
        return d


class FlightRecorder:
    """Fixed-size flight recorder for one engine (+ its server).

    Writers: the engine thread (step records, token/prefill events) and
    submitter threads ("queued" events). One lock guards the request
    dict and the ring slots; every hook takes it at most once and does
    O(1) work inside, so the recorder stays lock-cheap on the serve hot
    path. ``enabled=False`` (or detaching the recorder) short-circuits
    every hook to a single attribute check."""

    def __init__(self, capacity=4096, max_requests=2048, enabled=True,
                 replica=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.max_requests = int(max_requests)
        self.enabled = bool(enabled)
        #: replica/rank index in a multi-replica cluster: chrome-trace
        #: exports use it as the process id + process_name, so per-
        #: replica traces land in distinct lane groups and merge cleanly
        #: (merge_profile re-pids per file; the name survives). None =
        #: single-engine (os.getpid() lanes, unchanged).
        self.replica = replica
        self._ring: list[StepRecord | None] = [None] * self.capacity
        self._seq = 0                      # next step id
        self._lock = threading.Lock()
        self._live: dict[int, _RequestTrace] = {}
        self._done: collections.OrderedDict[int, _RequestTrace] = \
            collections.OrderedDict()
        #: step subscribers (the live pathology detectors): called with
        #: each COMPLETED StepRecord after finish_step, outside the
        #: recorder lock (a subscriber may take store/telemetry locks).
        #: Empty-list check is the only cost when nobody subscribes.
        self._subs = []

    # -- step subscribers (live detectors) ------------------------------
    def subscribe(self, fn):
        """Register ``fn(record)`` to run after every completed step —
        the live pathology detectors' feed. Runs on the engine thread;
        a raising subscriber is dropped from the next notification only
        by its own removal — exceptions are swallowed so a detector bug
        can never crash the serve loop."""
        self._subs.append(fn)
        return fn

    def unsubscribe(self, fn):
        try:
            self._subs.remove(fn)
        except ValueError:
            pass

    # -- step records (engine thread) -----------------------------------
    def next_step_id(self):
        """The id the next ``begin_step`` will assign — lets legacy
        admission stamp its prefill spans with the step that follows."""
        return self._seq

    def begin_step(self, *, scheduler, kind, grants, tokens_scheduled,
                   token_budget, queue_depth, free_blocks, total_blocks,
                   pipeline_inflight, preemptions, admit_s, schedule_s,
                   dispatch_s, t_begin, prefix_hit_tokens=None,
                   cached_blocks=None, readout_stride=1,
                   adapter_slots=(), adapter_swaps=0, kv_pool_bytes=None,
                   kv_cache_dtype=None, kv_swap_in_bytes=None,
                   kv_swap_out_bytes=None, kv_host_spill_blocks=None,
                   kv_ship_in_bytes=None, kv_ship_out_bytes=None):
        """Record one dispatched step; returns its step id."""
        with self._lock:
            sid = self._seq
            self._seq += 1
            self._ring[sid % self.capacity] = StepRecord(
                sid, t_begin, scheduler, kind, tuple(grants),
                int(tokens_scheduled), int(token_budget), int(queue_depth),
                free_blocks, total_blocks, int(pipeline_inflight),
                tuple(preemptions), admit_s, schedule_s, dispatch_s,
                prefix_hit_tokens=prefix_hit_tokens,
                cached_blocks=cached_blocks,
                readout_stride=int(readout_stride),
                adapter_slots=tuple(adapter_slots),
                adapter_swaps=int(adapter_swaps),
                kv_pool_bytes=kv_pool_bytes,
                kv_cache_dtype=kv_cache_dtype,
                kv_swap_in_bytes=kv_swap_in_bytes,
                kv_swap_out_bytes=kv_swap_out_bytes,
                kv_host_spill_blocks=kv_host_spill_blocks,
                kv_ship_in_bytes=kv_ship_in_bytes,
                kv_ship_out_bytes=kv_ship_out_bytes)
            return sid

    def finish_step(self, step_id, sync_s, emit_s, finished=(),
                    spec_accepted=0, spec_rejected=0):
        with self._lock:
            rec = self._ring[step_id % self.capacity]
            if rec is None or rec.step_id != step_id:
                return  # evicted by ring wrap between begin and finish
            rec.t_finish = time.perf_counter()
            rec.sync_s = sync_s
            rec.emit_s = emit_s
            rec.finished = tuple(finished)
            rec.spec_accepted = int(spec_accepted)
            rec.spec_rejected = int(spec_rejected)
        if self._subs:
            # OUTSIDE the recorder lock: subscribers (detectors) take
            # store/telemetry locks of their own, and nothing here may
            # deadlock or crash the engine thread
            for fn in tuple(self._subs):
                try:
                    fn(rec)
                except Exception:
                    pass

    def get_step(self, step_id):
        with self._lock:
            rec = self._ring[step_id % self.capacity]
            return rec if rec is not None and rec.step_id == step_id \
                else None

    def records(self):
        """The retained StepRecords, oldest first."""
        with self._lock:
            lo = max(0, self._seq - self.capacity)
            out = []
            for sid in range(lo, self._seq):
                rec = self._ring[sid % self.capacity]
                if rec is not None and rec.step_id == sid:
                    out.append(rec)
            return out

    def last_record(self):
        with self._lock:
            if not self._seq:
                return None
            rec = self._ring[(self._seq - 1) % self.capacity]
            return rec if rec is not None else None

    # -- request timelines ----------------------------------------------
    def _trace(self, rid, fresh=False):
        if not fresh:
            tr = self._live.get(rid)
            if tr is None:
                tr = self._done.get(rid)
            if tr is not None:
                return tr
        # first sighting — or a FRESH lifecycle ("queued"): request ids
        # restart per server, so a reused id must start a new timeline,
        # not resurrect the finished trace (whose stale last_token_t
        # would fabricate a giant phantom gap)
        self._done.pop(rid, None)
        tr = self._live[rid] = _RequestTrace(rid)
        if len(self._live) > self.max_requests:
            # bound _live too: a recorder attached directly to an
            # engine (no server, so no "finish" events) must not
            # grow without bound over a long-lived serve — demote
            # the oldest live trace to the bounded done set
            old_rid = next(iter(self._live))
            self._done[old_rid] = self._live.pop(old_rid)
            while len(self._done) > self.max_requests:
                self._done.popitem(last=False)
        return tr

    def req_event(self, rid, kind, step_id=None, value=None, t=None):
        """Append one lifecycle span event ("queued", "admitted",
        "prefill", "finish", ...) to request ``rid``'s timeline."""
        if not self.enabled:
            return
        if t is None:
            t = time.perf_counter()
        with self._lock:
            tr = self._trace(rid, fresh=(kind == "queued"))
            tr.events.append((kind, t, step_id, value))
            if kind == "cached_prefix":
                tr.prefix_hit = value
            if kind == "routed":
                tr.routing = value
            if kind == "finish":
                self._live.pop(rid, None)
                self._done[rid] = tr
                while len(self._done) > self.max_requests:
                    self._done.popitem(last=False)

    def set_trace_ctx(self, rid, ctx):
        """Stamp request ``rid``'s timeline with its distributed trace
        context (a TraceContext or its dict form). Called once per
        timeline, right after the "queued" event starts it — the stamp
        is what lets the merged cross-replica export group this lane
        with the same request's lanes on OTHER replicas."""
        if not self.enabled or ctx is None:
            return
        d = ctx if isinstance(ctx, dict) else ctx.to_dict()
        with self._lock:
            self._trace(rid).trace_ctx = dict(d)

    def on_token(self, rid, step_id, t=None):
        """Record one emitted token: its wall time, the id of the step
        whose readout produced it, and the gap since the request's
        previous token. THE per-token hot path — one lock, one tuple
        append. ``t``: an explicit stamp (the engine passes the token's
        AMORTIZED device-step-boundary time for multi-step readouts so
        a k-token burst doesn't read as one giant gap); stamps are
        clamped monotonic per request — pipelined strides may backdate
        into the previous readout's window."""
        if not self.enabled:
            return
        if t is None:
            t = time.perf_counter()
        with self._lock:
            tr = self._trace(rid)
            if tr.last_token_t is not None and t < tr.last_token_t:
                t = tr.last_token_t
            gap = t - tr.last_token_t if tr.last_token_t is not None \
                else None
            tr.last_token_t = t
            tr.events.append(("token", t, step_id, gap))

    def request_trace(self, rid):
        """JSON-ready timeline for one request (None if never seen or
        evicted)."""
        with self._lock:
            tr = self._live.get(rid) or self._done.get(rid)
            return tr.to_dict() if tr is not None else None

    def timelines(self):
        with self._lock:
            out = {}
            for src in (self._done, self._live):
                for rid, tr in src.items():
                    out[rid] = tr.to_dict()
            return out

    # -- exports --------------------------------------------------------
    def export_chrome_trace(self, path):
        """Write a chrome://tracing / Perfetto-loadable JSON: an
        engine-step lane (tid 0) with one span per StepRecord, plus one
        lane per request whose spans run from each timeline event's
        predecessor to the event itself ("queued" wait, "admitted",
        per-chunk "prefill[n]", per-token "token" gaps, "finish").
        Timestamps are perf_counter µs — the same clock and schema as
        ``Profiler._export_chrome``, so ``merge_profile`` can merge these
        with host profiles and across ranks."""
        pid = os.getpid() if self.replica is None else int(self.replica)
        events = []
        if self.replica is not None:
            # one lane GROUP per replica: the pid separates the groups
            # and the process_name labels them (merge_profile keeps the
            # label when it re-pids per merged file)
            events.append({"ph": "M", "pid": pid, "name": "process_name",
                           "args": {"name": f"replica {self.replica}"}})
        # PIPELINED steps overlap in time (step N+1 dispatches before
        # step N's sync), and same-tid 'X' events must nest properly —
        # pack overlapping step spans onto greedy sub-lanes (depth 2
        # needs exactly 2; requests live at tid >= 100)
        lane_ends = []
        for rec in self.records():
            t0 = rec.t_begin * 1e6
            dur = max(rec.wall_s * 1e6, 1.0)
            for lane, end in enumerate(lane_ends):
                if t0 >= end:
                    break
            else:
                lane = len(lane_ends)
                lane_ends.append(0.0)
            lane_ends[lane] = t0 + dur
            events.append({
                "ph": "X", "cat": "engine", "pid": pid, "tid": lane,
                "name": f"step {rec.step_id} [{rec.kind}]",
                "ts": t0, "dur": dur,
                "args": rec.to_dict()})
            # Perfetto COUNTER tracks ("ph": "C") — per-step load
            # context rendered as line charts UNDER the request lanes:
            # queue depth, pool occupancy, budget utilization, and the
            # speculative acceptance rate. One sample per StepRecord at
            # its dispatch time; series the record cannot source (dense
            # pools, non-spec steps) emit nothing rather than zeros.
            events.append({"ph": "C", "pid": pid, "name": "queue_depth",
                           "ts": t0,
                           "args": {"value": rec.queue_depth}})
            events.append({"ph": "C", "pid": pid,
                           "name": "token_budget_utilization", "ts": t0,
                           "args": {"value": round(
                               rec.budget_utilization, 4)}})
            if rec.total_blocks:
                occ = 1.0 - rec.free_blocks / rec.total_blocks
                events.append({"ph": "C", "pid": pid,
                               "name": "kv_pool_occupancy", "ts": t0,
                               "args": {"value": round(occ, 4)}})
            verified = rec.spec_accepted + rec.spec_rejected
            if verified:
                events.append({"ph": "C", "pid": pid,
                               "name": "spec_acceptance_rate", "ts": t0,
                               "args": {"value": round(
                                   rec.spec_accepted / verified, 4)}})
        for lane in range(max(len(lane_ends), 1)):
            events.append({
                "ph": "M", "pid": pid, "tid": lane, "name": "thread_name",
                "args": {"name": "engine steps" if lane == 0
                         else f"engine steps (pipelined +{lane})"}})
        for rid, tl in sorted(self.timelines().items()):
            tid = 100 + int(rid)  # tids < 100 are engine sub-lanes
            tc = tl.get("trace_ctx")
            lane = f"req {rid}" if tc is None else \
                f"req {rid} [{tc['trace_id']}/{tc['hop']}]"
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": lane}})
            prev_t = None
            for ev in tl["events"]:
                t_us = ev["t"] * 1e6
                start = prev_t if prev_t is not None else t_us
                name = ev["kind"]
                if name == "prefill":
                    name = f"prefill[{ev['value']}]"
                elif name == "cached_prefix":
                    name = f"cached_prefix[{ev['value']}]"
                elif name == "finish":
                    name = f"finish:{ev['value']}"
                args = {}
                if ev["step_id"] is not None:
                    args["step_id"] = ev["step_id"]
                if ev["kind"] == "token" and ev["value"] is not None:
                    args["gap_ms"] = round(ev["value"] * 1e3, 3)
                if ev["kind"] == "routed" and isinstance(ev["value"], dict):
                    args["routing"] = ev["value"]
                if tc is not None:
                    # every request-lane span carries its trace identity
                    # so the merged-trace stitcher can group lanes by
                    # trace_id WITHOUT re-reading recorder state (the
                    # merged file is all it has)
                    args["trace_id"] = tc["trace_id"]
                    args["trace_hop"] = tc["hop"]
                events.append({
                    "ph": "X", "cat": "request", "pid": pid, "tid": tid,
                    "name": name, "ts": start,
                    "dur": max(t_us - start, 1.0), "args": args})
                prev_t = t_us
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        return path

    # -- the slow-token explainer ---------------------------------------
    def explain_tail(self, quantile=0.99, top=None):
        """Join the worst inter-token gaps back to their causal
        StepRecord and name the dominant cause.

        Returns a list (worst gap first) of dicts: ``request_id``,
        ``gap_s``, ``step_id``, ``cause`` (one of :data:`TAIL_CAUSES`),
        and ``step`` (the record's facts, None when the ring evicted
        it). Cause taxonomy, checked in order against the step that
        emitted the token:

        * ``preempt_swap`` / ``preempt_reprefill`` — the step carried
          pool-pressure preemptions, split by whether the evicted KV
          moved through the host tier (swap bytes on the step) or was
          recomputed from scratch;
        * ``interfering_prefill`` — prefill work delayed the token: a
          chunk grant rode the same fused dispatch (Sarathi's per-step
          interference), or a legacy admission prefill train ran inside
          the step's ``admit_s`` split;
        * ``draft_rejected`` — the step's speculative verify windows
          rolled back more drafts than they committed: an acceptance
          stall (the adaptive-k EWMA should be shrinking the window),
          not a host-sync pathology;
        * ``batched_readout`` — the sync dominated but the step drained
          a multi-row token burst (``readout_stride > 1``: a multi-step
          stride, a legacy horizon scan, or spec verify windows): the
          gap is the amortized readout boundary working as designed
          (tune the stride/horizon, not the host);
        * ``host_sync`` — the device→host token sync dominated the step;
        * ``idle_bubble`` — the gap is mostly time OUTSIDE the step
          (the engine wasn't dispatching: admission trains, depth-1
          pipeline bubbles, loop stalls);
        * ``dispatch`` — the step's own device compute explains the gap.
        """
        gaps = []
        for rid, tl in self.timelines().items():
            # a token whose gap spans a supervised restart ("crashed"
            # span since the previous token) is a RECOVERY gap — its
            # causal step record describes the resumed engine, not the
            # stall, so it gets the dedicated cause label
            crashed_since = False
            for ev in tl["events"]:
                if ev["kind"] == "crashed":
                    crashed_since = True
                elif ev["kind"] == "token" and ev["value"] is not None:
                    gaps.append((ev["value"], rid, ev["step_id"],
                                 crashed_since))
                    crashed_since = False
                elif ev["kind"] == "token":
                    crashed_since = False
        if not gaps:
            return []
        ordered = sorted(g[0] for g in gaps)
        thresh = ordered[min(int(quantile * len(ordered)),
                             len(ordered) - 1)]
        tail = sorted((g for g in gaps if g[0] >= thresh), reverse=True)
        if top is not None:
            tail = tail[:top]
        out = []
        for gap, rid, sid, recovered in tail:
            rec = self.get_step(sid) if sid is not None else None
            cause = "restart_recovery" if recovered \
                else self._classify(gap, rec)
            entry = {"request_id": rid, "gap_s": round(gap, 6),
                     "step_id": sid, "cause": cause,
                     "step": rec.to_dict() if rec is not None else None}
            with self._lock:
                tr = self._live.get(rid) or self._done.get(rid)
                routing = tr.routing if tr is not None else None
                trace_ctx = tr.trace_ctx if tr is not None else None
            if trace_ctx is not None:
                entry["trace_id"] = trace_ctx["trace_id"]
            if routing is not None:
                # the router's placement record for THIS request — which
                # replica/score/affinity put the slow token where it ran
                entry["routing"] = routing
            if rec is not None and rec.prefix_hit_tokens is not None \
                    and cause == "interfering_prefill":
                # prefix cache was on and this gap came from prefill
                # interference: name whether any interfering REQUEST was
                # a COLD MISS (an admission the cache served nothing of).
                # Joined through the granted requests' own cached_prefix
                # records — the step's hit delta alone would mislabel
                # the later chunk grants of a partially-served prompt
                # (they ride steps whose own delta is 0)
                pre_rids = [g[1] for g in rec.grants if g[2] == "prefill"]
                if pre_rids:
                    with self._lock:
                        traces = [self._live.get(r) or self._done.get(r)
                                  for r in pre_rids]
                    entry["cold_miss"] = any(
                        tr is None or not tr.prefix_hit for tr in traces)
                else:
                    # legacy admit-train shape (no grants recorded):
                    # join through the prefill spans stamped with THIS
                    # step's id — one legacy step may admit several
                    # requests (cold and cache-served mixed in one
                    # train), so the step's own hit delta alone could
                    # hide a cold admission behind another's hit. Falls
                    # back to the delta when the timelines were evicted.
                    with self._lock:
                        hits = [tr.prefix_hit
                                for src in (self._live, self._done)
                                for tr in src.values()
                                if any(e[0] == "prefill" and e[2] == sid
                                       for e in tr.events)]
                    entry["cold_miss"] = any(not h for h in hits) \
                        if hits else rec.prefix_hit_tokens == 0
            out.append(entry)
        return out

    def classify_token_gap(self, rid, step_id, gap_s):
        """Classify ONE inter-token gap against its causal StepRecord —
        the single-gap form of :meth:`explain_tail`, for callers (the
        router's fleet-level tail join) that assemble END-TO-END gap
        lists across recorders and only need this recorder's verdict
        for a gap that stayed inside it. Returns ``(cause, record)``
        with record None when the ring evicted the step."""
        rec = self.get_step(step_id) if step_id is not None else None
        return self._classify(gap_s, rec), rec

    @staticmethod
    def _classify(gap, rec):
        if rec is None:
            return "unrecorded"
        if rec.preemptions:
            # split by the host KV tier's involvement: any tier traffic
            # on the step (swap-out at the preemption, or a swap-in
            # restore riding the same step's re-admission) means the
            # evicted KV moved through host RAM instead of being
            # recomputed — the cheap path, as opposed to the full
            # re-prefill the tier exists to remove
            if getattr(rec, "kv_swap_out_bytes", None) or \
                    getattr(rec, "kv_swap_in_bytes", None):
                return "preempt_swap"
            return "preempt_reprefill"
        if getattr(rec, "adapter_swaps", 0):
            # the step's admission swapped adapter factors onto the
            # device — a multi-tenant working set bigger than the
            # adapter cache, distinct from ordinary prefill ramp-in
            return "adapter_swap"
        if getattr(rec, "kv_ship_in_bytes", None) or \
                getattr(rec, "kv_ship_out_bytes", None):
            # cross-replica ship traffic rode this step (a migrated
            # request's import scattering in with its stitch grant, or
            # an export staging out at a finish) — checked BEFORE the
            # prefill-interference test because the stitch grant rides
            # a mixed step and would otherwise file there
            return "kv_ship"
        wall = rec.wall_s
        # prefill interference comes in two shapes: a fused chunk grant
        # in the step's own dispatch (grants), or a legacy admission
        # prefill train run inside step_begin (admit_s dominates the
        # wall — the single most common legacy stall)
        if rec.prefill_tokens > 0 or (wall > 0 and
                                      rec.admit_s >= 0.5 * wall):
            return "interfering_prefill"
        # rejection-stall refinement: only where the STEP ITSELF explains
        # the gap (sync- or dispatch-dominated below — never an idle
        # bubble, whose wall lies outside the step) AND a strict
        # majority of the step's verify work rolled back does the
        # rejected speculation own the verdict. Healthy-acceptance spec
        # steps keep the host_sync/batched_readout taxonomy.
        rejection_stall = getattr(rec, "spec_rejected", 0) > \
            getattr(rec, "spec_accepted", 0)
        if wall > 0 and rec.sync_s >= 0.5 * wall:
            if rejection_stall:
                # the sync drained windows that mostly rolled back: the
                # wall went to verifying tokens that never committed —
                # an acceptance stall, NOT the host-sync pathology the
                # share heuristic would otherwise file it as
                return "draft_rejected"
            # a sync-dominated step whose readout drained a k-row burst
            # (stride, horizon scan, or spec verify windows) is the
            # BATCHED readout boundary, not a host-sync pathology — one
            # sync amortized over k rows per slot is exactly what those
            # amortization knobs are for
            if rec.readout_stride > 1:
                return "batched_readout"
            return "host_sync"
        if gap - wall > max(wall, 1e-9):
            return "idle_bubble"
        if rejection_stall:
            # dispatch-dominated verify step, majority rolled back: the
            # device compute was spent on rejected drafts
            return "draft_rejected"
        return "dispatch"

    def snapshot(self, tail=None):
        """JSON-ready summary: retained step counts + cause histogram of
        the current 0.99 tail (cheap enough to ride in bench output).
        Pass a precomputed ``explain_tail`` result as ``tail`` to avoid
        re-walking the timelines."""
        recs = self.records()
        if tail is None:
            tail = self.explain_tail(0.99, top=64)
        causes = collections.Counter(e["cause"] for e in tail)
        return {"steps_recorded": len(recs),
                "steps_total": self._seq,
                "ring_capacity": self.capacity,
                "requests_tracked": len(self._live) + len(self._done),
                "tail_causes_p99": dict(causes)}
