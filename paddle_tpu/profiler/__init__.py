"""paddle.profiler analog (reference: python/paddle/profiler/profiler.py:358,
utils.py:47 RecordEvent, profiler_statistic.py, timer.py).

Two coordinated layers, like the reference (SURVEY.md §5.1):
1. Host events: RecordEvent context manager -> in-process buffer ->
   export_chrome_tracing writes a chrome://tracing JSON.
2. Device profile: jax.profiler start/stop trace (xplane -> TensorBoard /
   Perfetto), the TPU-native replacement for the CUPTI tracer.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum

from .timer import benchmark  # noqa: F401
from .serving_telemetry import (  # noqa: F401
    LABELED_GAUGE_FAMILIES, LatencyHistogram, ServingTelemetry)
from .flight_recorder import (  # noqa: F401
    COUNTER_TRACKS, FLOW_EVENT_NAME, FlightRecorder, REQUEST_EVENT_KINDS,
    StepRecord, TAIL_CAUSES)
from .black_box import (  # noqa: F401
    BlackBox, BUNDLE_SCHEMA, collect_bundle, TRIGGER_REASONS,
    write_bundle)
from .metrics_store import (  # noqa: F401
    Alert, ALERT_KINDS, MetricsStore, Series)
from .slo import (  # noqa: F401
    SLO, SLOEngine, default_detectors, evaluate_slo, format_slo_report)

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
    "summarize_device_trace",
    "SummaryView", "benchmark", "merge_profile",
    "ServingTelemetry", "LatencyHistogram", "LABELED_GAUGE_FAMILIES",
    "FlightRecorder", "StepRecord", "TAIL_CAUSES",
    "REQUEST_EVENT_KINDS", "COUNTER_TRACKS", "FLOW_EVENT_NAME",
    "BlackBox", "collect_bundle", "write_bundle", "BUNDLE_SCHEMA",
    "TRIGGER_REASONS",
    "MetricsStore", "Series", "Alert", "ALERT_KINDS",
    "SLO", "SLOEngine", "default_detectors", "evaluate_slo",
    "format_slo_report",
]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class _EventBuffer:
    def __init__(self):
        self.events = []
        self.enabled = False
        self.lock = threading.Lock()

    def add(self, name, ts, dur, tid):
        if self.enabled:
            with self.lock:
                self.events.append({"name": name, "ts": ts, "dur": dur,
                                    "tid": tid})


_BUFFER = _EventBuffer()


class RecordEvent:
    """Host-side scope event (reference: profiler/utils.py:47). While a
    profiler is recording it also enters a jax named_scope so the span
    shows up inside device traces under jit.

    When NO profiler is recording, enter/exit is a single flag check —
    no clock read, no jax import, no named_scope — so always-on
    instrumentation (library internals wrapping hot paths in
    RecordEvent) costs ~nothing in production. A profiler that starts
    recording mid-event picks the event up from its NEXT entry."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._scope = None

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__(None, None, None)

    def __enter__(self):
        if not _BUFFER.enabled:
            self._t0 = None  # disabled fast path: nothing to undo on exit
            self._scope = None
            return self
        self._t0 = time.perf_counter_ns()
        try:
            import jax
            self._scope = jax.named_scope(self.name)
            self._scope.__enter__()
        except Exception:
            self._scope = None
        return self

    def __exit__(self, *exc):
        if self._scope is not None:
            self._scope.__exit__(*exc)
        if self._t0 is None:
            return False  # entered while disabled: no span to record
        t1 = time.perf_counter_ns()
        _BUFFER.add(self.name, self._t0 / 1e3, (t1 - self._t0) / 1e3,
                    threading.get_ident())
        return False


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """Step-state schedule closure (reference: profiler.py make_scheduler)."""
    period = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready callback factory (reference: profiler.py:227)."""
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}"
                                      ".paddle_trace.json")
        prof._export_chrome(path)
        return path
    return handler


def export_protobuf(dir_name, worker_name=None):  # parity stub -> chrome json
    return export_chrome_tracing(dir_name, worker_name)


class Profiler:
    """Reference: profiler/profiler.py:358. step()-driven scheduler states;
    on_trace_ready fires at RECORD_AND_RETURN boundaries.

    When `timer_only=False` and a TPU/devices are present, a jax.profiler trace
    (xplane) is captured alongside host events into `trace_dir`."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 trace_dir=None, emit_nvtx=False, custom_device_types=None):
        if scheduler is None:
            self._schedule = lambda step: ProfilerState.RECORD
        elif callable(scheduler):
            self._schedule = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            start, end = scheduler
            self._schedule = make_scheduler(closed=max(start, 0), ready=0,
                                            record=end - start, repeat=1)
        else:
            raise TypeError(f"bad scheduler: {scheduler!r}")
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.trace_dir = trace_dir
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._device_trace_on = False
        self._events_snapshot = []

    # -- lifecycle ------------------------------------------------------
    def start(self):
        self.current_state = self._schedule(self.step_num)
        self._apply_state()

    def stop(self):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._finish_record()
        _BUFFER.enabled = False
        self._stop_device_trace()
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples=None):
        benchmark().step(num_samples)
        old = self.current_state
        self.step_num += 1
        self.current_state = self._schedule(self.step_num)
        recording = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        # finish on the scheduled boundary OR any transition out of recording
        if old == ProfilerState.RECORD_AND_RETURN or (
                old in recording and self.current_state not in recording):
            self._finish_record()
        self._apply_state()

    def step_info(self, unit=None):
        return benchmark().step_info(unit)

    def _apply_state(self):
        st = self.current_state
        _BUFFER.enabled = st in (ProfilerState.RECORD,
                                 ProfilerState.RECORD_AND_RETURN)
        if _BUFFER.enabled and not self.timer_only:
            self._start_device_trace()
        elif not _BUFFER.enabled:
            self._stop_device_trace()

    def _start_device_trace(self):
        if self._device_trace_on or self.trace_dir is None:
            return
        try:
            import jax
            jax.profiler.start_trace(self.trace_dir)
            self._device_trace_on = True
        except Exception:
            self._device_trace_on = False

    def _stop_device_trace(self):
        if self._device_trace_on:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_trace_on = False

    def _finish_record(self):
        with _BUFFER.lock:
            self._events_snapshot = list(_BUFFER.events)
            _BUFFER.events.clear()
        self._stop_device_trace()
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- export/summary -------------------------------------------------
    def _export_chrome(self, path):
        events = [{"ph": "X", "cat": "host", "pid": os.getpid(),
                   "tid": e["tid"], "name": e["name"], "ts": e["ts"],
                   "dur": e["dur"]} for e in self._events_snapshot]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def export(self, path, format="json"):
        self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated host-event table (reference: profiler_statistic.py)."""
        agg = {}
        for e in self._events_snapshot:
            a = agg.setdefault(e["name"], [0, 0.0, 0.0])
            a[0] += 1
            a[1] += e["dur"]
            a[2] = max(a[2], e["dur"])
        div = {"ms": 1e3, "us": 1.0, "s": 1e6}[time_unit]
        lines = [f"{'Name':<40} {'Calls':>8} {'Total(' + time_unit + ')':>14} "
                 f"{'Avg':>10} {'Max':>10}"]
        for name, (cnt, tot, mx) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name[:40]:<40} {cnt:>8} {tot / div:>14.4f} "
                         f"{tot / cnt / div:>10.4f} {mx / div:>10.4f}")
        table = "\n".join(lines)
        print(table)
        return table


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def load_profiler_result(filename):
    """Load an exported chrome-trace JSON back as a list of events."""
    with open(filename) as f:
        return json.load(f).get("traceEvents", [])


def summarize_device_trace(events):
    """Aggregate the DEVICE tracks of an XLA chrome trace into
    ``({instr_name: {"count", "total_us"}}, module_total_us)`` — THE
    dedupe-aware trace parser (ROUND5_NOTES "found along the way"):

    a device lane carries THREE overlapping span families — ``jit_*``
    module spans (the true device step time), the per-instruction op
    spans nested inside them, and the "Steps" track's bare-number step
    markers, which cover the same wall time as the module spans. A tool
    that naively sums every device span therefore double-counts step
    time once via the step markers and again via the modules (and
    triple-counts it via the ops). Here each family is routed exactly
    once: ``jit_*`` spans sum into ``module_total_us``, per-op spans
    aggregate by name, and bare-number step markers count toward
    NEITHER.

    ``events``: a ``traceEvents`` list (e.g. from
    :func:`load_profiler_result`). Device lanes are recognized by their
    ``process_name`` metadata containing ``device:TPU``."""
    device_pids = set()
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and "device:TPU" in str(e.get("args", {}).get("name", ""))):
            device_pids.add(e["pid"])
    agg = {}
    module_total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        name = e["name"]
        dur = float(e.get("dur", 0.0))
        if name.startswith("jit_"):
            module_total += dur
            continue
        if name.isdigit():
            # "Steps" track marker: overlaps the module spans it brackets
            continue
        entry = agg.setdefault(name, {"count": 0, "total_us": 0.0})
        entry["count"] += 1
        entry["total_us"] += dur
    return agg, module_total


def merge_profile(rank_dirs_or_files, output_path, align_start=True):
    """Merge per-rank chrome traces into one cluster-wide timeline.

    Reference: tools/CrossStackProfiler/ (merges per-rank profiles into a
    single view for cluster-wide hang/straggler diagnosis — SURVEY.md §5.1).
    Each rank's events land in their own process lane (pid = rank index, with
    a process_name metadata row); with align_start, per-rank clocks are
    shifted so every rank's first event starts at t=0, compensating unsynced
    host clocks.
    """
    import glob
    import re

    def _natural(s):
        return [int(t) if t.isdigit() else t
                for t in re.split(r"(\d+)", os.path.basename(s))]

    files = []
    for entry in rank_dirs_or_files:
        if os.path.isdir(entry):
            # natural sort so rank10 sorts after rank9, not after rank1
            files.extend(sorted(glob.glob(os.path.join(entry, "*.json")),
                                key=_natural))
        else:
            files.append(entry)
    if not files:
        raise ValueError("no trace files to merge")

    merged = []
    for rank, path in enumerate(files):
        with open(path) as f:
            trace = json.load(f)
        events = trace["traceEvents"] if isinstance(trace, dict) else trace
        t0 = min((e["ts"] for e in events
                  if e.get("ph") != "M" and "ts" in e), default=0)
        shift = -t0 if align_start else 0
        label = os.path.splitext(os.path.basename(path))[0]
        merged.append({"ph": "M", "pid": rank, "name": "process_name",
                       "args": {"name": f"rank{rank}:{label}"}})
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                continue  # replaced by the rank lane name
            e = dict(e)
            e["pid"] = rank
            if "ts" in e:
                e["ts"] = e["ts"] + shift
            merged.append(e)

    os.makedirs(os.path.dirname(output_path) or ".", exist_ok=True)
    with open(output_path, "w") as f:
        json.dump({"traceEvents": merged}, f)
    return output_path


class SortedKeys(Enum):
    """Sort orders for summary tables (reference: profiler/profiler.py
    SortedKeys)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


__all__.append("SortedKeys")
