"""Per-stage serving telemetry — the observability layer of
``paddle_tpu.serving`` (reference analog: the serving-side statistics the
reference's AnalysisPredictor/PaddleNLP stack exposes through
paddle.profiler summaries; here the consumer is a SERVER loop, so the
shapes are production-serving shapes: stage wall clocks, counters, and
latency histograms with a Prometheus-style text export).

Three pieces, all thread-safe (the engine thread writes, any thread
snapshots):

* **stage clocks** — monotonic wall-time accumulators for the named
  phases of the serve loop (``queue_admit``, ``prefill_dispatch``,
  ``schedule``, ``decode_dispatch``, ``host_sync``, ``emit``, ``idle``).
  ``attribution(wall_s)`` reports each stage's share of a wall-clock
  window and the total attributed fraction — the number the round-5
  verdict found missing (only 24% of serve wall was explained; the
  acceptance bar here is ≥90%).
* **counters** — requests submitted/admitted/finished/cancelled/expired/
  rejected, tokens emitted, engine steps.
* **gauges** — point-in-time engine state the server samples every loop
  pass: queue depth, running/waiting slots, KV-pool occupancy, token
  budget utilization, pipeline dispatches in flight.
* **latency histograms** — TTFT, inter-token gap, end-to-end, and queue
  wait, on log-spaced buckets with quantile estimates.

Names are STRICT: ``add_stage``/``inc``/``set_gauge`` raise ``KeyError``
for a name that was never declared — a typo'd stage or counter name must
fail loudly instead of silently forking the attribution into a phantom
key. Extensions declare their names first via :meth:`ServingTelemetry
.register` (they survive :meth:`reset`).

Export: :meth:`ServingTelemetry.snapshot` (JSON-ready dict) and
:meth:`ServingTelemetry.prometheus_text` (text exposition format).
"""
from __future__ import annotations

import bisect
import contextlib
import threading
import time

__all__ = ["LatencyHistogram", "ServingTelemetry", "STAGES", "GAUGES",
           "LABELED_GAUGE_FAMILIES"]

#: the named stages of the serve loop, in pipeline order. Every second of
#: busy engine-thread wall time lands in exactly one of these (or in
#: "other", the loop's own bookkeeping remainder).
STAGES = ("queue_admit", "prefill_dispatch", "schedule", "decode_dispatch",
          "host_sync", "emit", "idle", "other")

#: point-in-time gauges the serve loop samples each pass (pool gauges
#: stay 0 on the dense engine; budget utilization needs the flight
#: recorder's last StepRecord and stays 0 without one; prefix gauges
#: stay 0 unless the engine runs enable_prefix_cache). server_healthy
#: is the health-protocol gauge: 1 while the serve loop heartbeats, 0
#: when the watchdog declares it hung or a crash lands — the replica
#: router's failover signal, and 0 on a never-started server.
GAUGES = ("queue_depth", "engine_waiting", "running_slots",
          "pipeline_inflight", "kv_pool_free_blocks", "kv_pool_occupancy",
          "token_budget_utilization", "prefix_cached_blocks",
          "prefix_cache_hit_rate", "server_healthy",
          "adapter_cache_occupancy",
          # speculative serving: cumulative accepted/proposed draft
          # ratio (stays 0 on non-speculative engines)
          "spec_acceptance_rate",
          # quantized KV serving: pool capacity in BF16-EQUIVALENT block
          # counts (n_blocks unquantized, ~2x/~4x under int8/int4) —
          # one capacity number comparable across kv_cache_dtype arms
          "kv_pool_effective_blocks",
          # host KV tier: cumulative bytes moved each way by the
          # PREEMPTION-SWAP half (spill/promote traffic counts blocks on
          # kv_spill_blocks/kv_promote_blocks instead — the swap bytes
          # double as the preempt_swap classifier signal), and the host
          # spill store's current block count (all 0 with the tier off)
          "kv_swap_in_bytes", "kv_swap_out_bytes", "kv_host_spill_blocks",
          # the spill store's byte occupancy — same store as
          # kv_host_spill_blocks, in the unit its bound is set in
          "kv_host_spill_bytes",
          # gauge STALENESS: seconds since the serve loop last sampled
          # the point-in-time gauges (mark_gauge_sample). Computed at
          # READ time from the sampling stamp — a hung/idle loop's
          # stale gauges are visible as a GROWING age instead of
          # silently frozen values (the watchdog's hung flip does not
          # refresh it: only a real loop pass does)
          "gauge_last_sample_age_s")

#: labeled gauge FAMILIES — dynamic-label metric families (like
#: tenant_tokens): the SLO engine's per-objective burn gauges and the
#: live pathology detectors' active flags. Family -> its label key.
#: Families are schema (strict: set_labeled_gauge raises KeyError on an
#: unknown one, and the PTL007 analysis pass checks call sites); the
#: label VALUES (slo names, detector kinds) are data.
LABELED_GAUGE_FAMILIES = {"slo_burn_rate": "slo",
                          "slo_breached": "slo",
                          "pathology_active": "kind"}

#: latency families that keep PER-TENANT histograms alongside the
#: global ones (observe(..., tenant=i)); admission_stall stays global
#: (admission is a shared-queue property, not a tenant one).
_TENANT_HISTS = ("ttft_s", "inter_token_s", "e2e_s", "queue_wait_s")

_COUNTERS = ("requests_submitted", "requests_admitted", "requests_finished",
             "requests_cancelled", "requests_expired",
             "requests_rejected_queue_full", "requests_rejected_validation",
             "requests_shed_deadline", "requests_resumed",
             "engine_restarts", "faults_injected", "tokens_emitted",
             "engine_steps", "multi_steps", "preemptions", "prefill_tokens",
             "prefix_hit_tokens", "prefix_cow_blocks",
             "prefix_evicted_blocks",
             "adapter_cache_hits", "adapter_cache_misses", "adapter_swaps",
             "embed_requests",
             "spec_proposed_tokens", "spec_accepted_tokens",
             # host KV tier: blocks swapped out at preemption / restored
             # at re-admission, re-prefill tokens the restores avoided,
             # and prefix blocks spilled to / promoted from the host
             # store
             "kv_swap_out_blocks", "kv_swap_in_blocks",
             "kv_swap_saved_tokens", "kv_spill_blocks",
             "kv_promote_blocks",
             # disaggregated serving: cross-replica KV shipped out of /
             # into this replica (staged-entry exports + pull-on-miss
             # prefix blocks) — booked apart from the swap counters so
             # the preemption classifier's signal stays exclusive
             "kv_ship_out_blocks", "kv_ship_in_blocks",
             "kv_ship_out_bytes", "kv_ship_in_bytes")


def _default_bounds():
    """Log-spaced bucket upper bounds: 0.1 ms .. ~105 s, x2 per bucket —
    21 buckets cover sub-ms token gaps and multi-second e2e latencies."""
    return tuple(1e-4 * (2.0 ** i) for i in range(21))


class LatencyHistogram:
    """Fixed-bucket latency histogram (seconds). Cheap enough for the
    per-token hot path: one bisect + three adds per observation."""

    __slots__ = ("bounds", "counts", "count", "total", "minimum", "maximum")

    def __init__(self, bounds=None):
        self.bounds = tuple(bounds) if bounds is not None \
            else _default_bounds()
        self.counts = [0] * (len(self.bounds) + 1)  # +1 = overflow bucket
        self.count = 0
        self.total = 0.0
        self.minimum = float("inf")
        self.maximum = 0.0

    def observe(self, v):
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.total += v
        self.minimum = min(self.minimum, v)
        self.maximum = max(self.maximum, v)

    @property
    def mean(self):
        return self.total / self.count if self.count else 0.0

    def quantile(self, q):
        """Upper-bound estimate of the q-quantile from bucket counts (the
        bucket's upper bound; overflow bucket reports the observed max)."""
        if not self.count:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank and c:
                return self.bounds[i] if i < len(self.bounds) \
                    else self.maximum
        return self.maximum

    def snapshot(self):
        return {"count": self.count,
                "mean_s": round(self.mean, 6),
                "min_s": round(self.minimum, 6) if self.count else 0.0,
                "max_s": round(self.maximum, 6),
                "p50_s": round(self.quantile(0.5), 6),
                "p90_s": round(self.quantile(0.9), 6),
                "p99_s": round(self.quantile(0.99), 6)}

    def copy(self):
        out = LatencyHistogram(self.bounds)
        out.counts = list(self.counts)
        out.count = self.count
        out.total = self.total
        out.minimum = self.minimum
        out.maximum = self.maximum
        return out

    def merge(self, other):
        """BUCKET-WISE merge of another histogram into this one — the
        fleet aggregation primitive (N replicas' per-tenant histograms
        sum into one whose quantile estimates are exact at bucket
        resolution, which per-replica quantiles can never recombine
        into). Requires identical bucket bounds."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different "
                             "bucket bounds")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        if other.count:
            self.minimum = min(self.minimum, other.minimum)
            self.maximum = max(self.maximum, other.maximum)
        return self

    def prometheus_lines(self, name, labels="", type_line=True):
        """Cumulative-bucket exposition lines (histogram type).
        ``labels``: extra label body WITHOUT braces or leading comma
        (e.g. ``replica="0"``) — composed correctly into both the
        ``le``-labeled bucket lines and the bare sum/count lines.
        ``type_line=False`` omits the ``# TYPE`` header — for extra
        labeled series (per-tenant) of a family whose header an earlier
        histogram already emitted (a repeated TYPE line within one
        exposition is invalid)."""
        sep = ("," + labels) if labels else ""
        bare = ("{" + labels + "}") if labels else ""
        lines = [f"# TYPE {name} histogram"] if type_line else []
        acc = 0
        for bound, c in zip(self.bounds, self.counts):
            acc += c
            lines.append(f'{name}_bucket{{le="{bound:g}"{sep}}} {acc}')
        lines.append(f'{name}_bucket{{le="+Inf"{sep}}} {self.count}')
        lines.append(f"{name}_sum{bare} {self.total:g}")
        lines.append(f"{name}_count{bare} {self.count}")
        return lines


class ServingTelemetry:
    """The serve loop's stage clocks + counters + latency histograms.

    ``replica``: this telemetry's replica/rank index in a multi-replica
    cluster — every Prometheus line gains a ``replica="i"`` label so N
    replicas' scrapes aggregate instead of colliding, and snapshots
    carry the index. None = unlabeled single-server output (unchanged
    schema)."""

    def __init__(self, replica=None):
        self._lock = threading.Lock()
        self.replica = replica
        #: extension names declared via register(); they survive reset()
        self._extra = {"stage": set(), "counter": set(), "gauge": set()}
        self.reset()

    def register(self, kind, name):
        """Declare an EXTENSION stage/counter/gauge name — the escape
        hatch from the strict-name contract (unknown names raise
        KeyError so a typo can't silently fork the attribution into a
        phantom key). Registered names survive :meth:`reset`."""
        if kind not in ("stage", "counter", "gauge"):
            raise ValueError(f"register kind must be 'stage', 'counter' or "
                             f"'gauge', got {kind!r}")
        with self._lock:
            self._extra[kind].add(name)
            target = {"stage": self.stage_s, "counter": self.counters,
                      "gauge": self.gauges}[kind]
            target.setdefault(name, 0.0 if kind != "counter" else 0)

    def reset(self):
        with self._lock:
            self.started_at = time.perf_counter()
            self.stage_s = {name: 0.0 for name in STAGES}
            self.stage_s.update({n: 0.0 for n in self._extra["stage"]})
            self.counters = {name: 0 for name in _COUNTERS}
            self.counters.update({n: 0 for n in self._extra["counter"]})
            self.gauges = {name: 0.0 for name in GAUGES}
            self.gauges.update({n: 0.0 for n in self._extra["gauge"]})
            #: gauge STALENESS stamps (time.monotonic): per-gauge write
            #: times plus the serve loop's whole-pass sampling mark —
            #: gauge_last_sample_age_s is computed from these at READ
            #: time, so a hung loop's frozen gauges age visibly
            self.gauge_stamps = {}
            self._started_mono = time.monotonic()
            self._gauge_sample_t = None
            #: labeled gauge families (slo_burn_rate{slo=...},
            #: pathology_active{kind=...}): family -> {label: value}.
            #: Families are schema (LABELED_GAUGE_FAMILIES), labels are
            #: data — same split as tenant_tokens.
            self.labeled_gauges = {n: {} for n in LABELED_GAUGE_FAMILIES}
            #: per-TENANT latency histograms (adapter_id -> {family:
            #: LatencyHistogram}), populated lazily by observe(...,
            #: tenant=i) ALONGSIDE the global families — the per-tenant
            #: p99s the SLO layer scopes objectives against
            self.tenant_latency = {}
            #: per-TENANT processed-token counters (adapter_id ->
            #: tokens): generated tokens per tenant, plus an embed
            #: request's pooled prompt tokens at its finish. Tenant ids
            #: are data, not schema — a dynamic label on one metric
            #: family, outside the strict-name counter contract.
            self.tenant_tokens = {}
            self.ttft_s = LatencyHistogram()
            self.inter_token_s = LatencyHistogram()
            self.e2e_s = LatencyHistogram()
            self.queue_wait_s = LatencyHistogram()
            #: time a waiting request spent queued AFTER a free slot
            #: existed — admission lag behind capacity. The legacy
            #: admit-then-decode path pays it whenever prefill trains
            #: block the loop; the fused scheduler drives it to ~0.
            self.admission_stall_s = LatencyHistogram()

    # -- write side (engine thread + submitters) ------------------------
    def add_stage(self, name, dt):
        if dt <= 0.0 and name in self.stage_s:
            return
        with self._lock:
            if name not in self.stage_s:
                raise KeyError(
                    f"unknown telemetry stage {name!r} (a typo here would "
                    f"silently fork the attribution) — declare it with "
                    f"register('stage', {name!r}) first")
            self.stage_s[name] += dt

    @contextlib.contextmanager
    def stage(self, name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_stage(name, time.perf_counter() - t0)

    def inc(self, name, n=1):
        with self._lock:
            if name not in self.counters:
                raise KeyError(
                    f"unknown telemetry counter {name!r} — declare it with "
                    f"register('counter', {name!r}) first")
            self.counters[name] += n

    def inc_tenant(self, tenant, n=1):
        """Count ``n`` processed tokens against ``tenant`` (an adapter
        id; 0 = base). Tenants are dynamic data, so this is the one
        write-side entry point that does NOT require registration."""
        with self._lock:
            key = int(tenant)
            self.tenant_tokens[key] = self.tenant_tokens.get(key, 0) + n

    def set_gauge(self, name, value):
        with self._lock:
            if name not in self.gauges:
                raise KeyError(
                    f"unknown telemetry gauge {name!r} — declare it with "
                    f"register('gauge', {name!r}) first")
            self.gauges[name] = float(value)
            self.gauge_stamps[name] = time.monotonic()

    def set_labeled_gauge(self, family, label, value):
        """Set one labeled gauge sample (``family{<key>="<label>"}``).
        The FAMILY must be declared in :data:`LABELED_GAUGE_FAMILIES`
        (strict, like set_gauge); the label value is dynamic data (an
        SLO name, a detector kind)."""
        with self._lock:
            if family not in self.labeled_gauges:
                raise KeyError(
                    f"unknown labeled gauge family {family!r} — declare "
                    f"it in LABELED_GAUGE_FAMILIES")
            self.labeled_gauges[family][str(label)] = float(value)

    def mark_gauge_sample(self):
        """Stamp 'the serve loop sampled the gauges NOW' — the write
        side of ``gauge_last_sample_age_s``. Called once per loop pass
        (after ``_update_gauges``); deliberately NOT called by the
        watchdog or any out-of-loop writer, so a hung loop's age keeps
        growing even while the watchdog flips ``server_healthy``."""
        with self._lock:
            self._gauge_sample_t = time.monotonic()

    def _gauge_age_locked(self, now=None):
        """Seconds since the last loop-pass gauge sample (uptime when
        none happened yet). Caller holds the lock."""
        if now is None:
            now = time.monotonic()
        base = self._gauge_sample_t if self._gauge_sample_t is not None \
            else self._started_mono
        return max(now - base, 0.0)

    def observe(self, hist_name, v, tenant=None):
        """Observe one latency sample. With ``tenant`` set, the sample
        ALSO lands in that tenant's histogram of the same family
        (created lazily) — ``hist_name`` must then be one of
        :data:`_TENANT_HISTS` (strict)."""
        with self._lock:
            getattr(self, hist_name).observe(v)
            if tenant is None:
                return
            if hist_name not in _TENANT_HISTS:
                raise KeyError(
                    f"telemetry histogram {hist_name!r} has no per-tenant "
                    f"variant (families: {_TENANT_HISTS})")
            fams = self.tenant_latency.get(int(tenant))
            if fams is None:
                fams = self.tenant_latency[int(tenant)] = {
                    n: LatencyHistogram() for n in _TENANT_HISTS}
            fams[hist_name].observe(v)

    # -- read side ------------------------------------------------------
    def get_gauges(self):
        """Point-in-time copy of every gauge — the replica router's
        load-scoring read (one lock, one dict copy).
        ``gauge_last_sample_age_s`` is computed here, at read time: the
        stored 0.0 would claim freshness a hung loop does not have."""
        with self._lock:
            out = dict(self.gauges)
            out["gauge_last_sample_age_s"] = self._gauge_age_locked()
            return out

    def get_counters(self):
        """Point-in-time copy of every counter — the metrics-store
        feed's read (counter deltas become windowed rate() series)."""
        with self._lock:
            return dict(self.counters)

    def tenant_latency_hists(self):
        """Deep-copied per-tenant histograms ``{tenant: {family:
        LatencyHistogram}}`` — the fleet merge's input (copies, so the
        router's bucket-wise merge never mutates live telemetry)."""
        with self._lock:
            return {t: {n: h.copy() for n, h in fams.items()}
                    for t, fams in self.tenant_latency.items()}

    @staticmethod
    def render_tenant_latency(hists):
        """JSON-ready rendering of a ``{tenant: {family_name:
        LatencyHistogram}}`` map (family names lose their ``_s``
        suffix, mirroring the global ``latency`` snapshot keys) — THE
        one copy, shared by snapshot(), the server's slo_report and
        the router's fleet merge."""
        return {str(t): {n[:-2]: h.snapshot() for n, h in fams.items()}
                for t, fams in sorted(hists.items())}

    def tenant_latency_snapshot(self):
        """The per-tenant latency block as snapshot()/slo_report()
        expose it."""
        return self.render_tenant_latency(self.tenant_latency_hists())

    def attribution(self, wall_s=None, include_idle=False):
        """Per-stage share of ``wall_s`` (default: telemetry uptime) and
        the summed ``attributed_share`` — how much of the serve wall the
        named stages explain. ``idle`` is excluded by default so a mostly
        idle server doesn't trivially 'attribute' its wall."""
        with self._lock:
            stages = dict(self.stage_s)
            uptime = time.perf_counter() - self.started_at
        wall = wall_s if wall_s and wall_s > 0 else uptime
        named = {k: v for k, v in stages.items()
                 if include_idle or k != "idle"}
        shares = {k: round(v / wall, 4) for k, v in named.items()}
        return {"wall_s": round(wall, 4),
                "stage_share": shares,
                "attributed_share": round(
                    min(sum(named.values()) / wall, 1.0), 4)}

    def snapshot(self, wall_s=None):
        """JSON-ready snapshot: uptime, counters, per-stage seconds and
        shares, latency histograms."""
        with self._lock:
            out = {
                "replica": self.replica,
                "uptime_s": round(time.perf_counter() - self.started_at, 4),
                "counters": dict(self.counters),
                "tenant_tokens": {str(k): v for k, v
                                  in sorted(self.tenant_tokens.items())},
                "gauges": {k: round(v, 6) for k, v in self.gauges.items()},
                "labeled_gauges": {fam: dict(vals) for fam, vals
                                   in self.labeled_gauges.items()},
                "stages_s": {k: round(v, 6)
                             for k, v in self.stage_s.items()},
                "latency": {
                    "ttft": self.ttft_s.snapshot(),
                    "inter_token": self.inter_token_s.snapshot(),
                    "e2e": self.e2e_s.snapshot(),
                    "queue_wait": self.queue_wait_s.snapshot(),
                    "admission_stall": self.admission_stall_s.snapshot(),
                },
                "tenant_latency": self.render_tenant_latency(
                    self.tenant_latency),
            }
            out["gauges"]["gauge_last_sample_age_s"] = round(
                self._gauge_age_locked(), 6)
            now = time.monotonic()
            out["gauge_ages"] = {k: round(now - t, 6) for k, t
                                 in sorted(self.gauge_stamps.items())}
            prefill = self.counters["prefill_tokens"]
            decode = self.counters["tokens_emitted"]
            #: share of all processed tokens that were PREFILL — how much
            #: of the serve work is ramp-in (the fused scheduler's
            #: interference budget is about bounding this per step)
            out["prefill_token_share"] = round(
                prefill / (prefill + decode), 4) if prefill + decode else 0.0
        out["attribution"] = self.attribution(wall_s)
        return out

    def prometheus_text(self, prefix="paddle_tpu_serving"):
        """Prometheus text exposition: counters, gauges, stage-seconds
        counters, latency histograms. With ``replica`` set, every line
        carries ``replica="i"`` so a multi-replica scrape endpoint can
        concatenate N replicas' dumps without series collisions."""
        with self._lock:
            rep = self.replica
            lbl = f'replica="{rep}"' if rep is not None else ""
            brace = ("{" + lbl + "}") if lbl else ""
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            gauges["gauge_last_sample_age_s"] = self._gauge_age_locked()
            stages = dict(self.stage_s)
            hists = {"ttft_seconds": self.ttft_s,
                     "inter_token_seconds": self.inter_token_s,
                     "e2e_seconds": self.e2e_s,
                     "queue_wait_seconds": self.queue_wait_s,
                     "admission_stall_seconds": self.admission_stall_s}
            prefill = self.counters["prefill_tokens"]
            decode = self.counters["tokens_emitted"]
            share = prefill / (prefill + decode) if prefill + decode else 0.0
            lines = [f"# TYPE {prefix}_prefill_token_share gauge",
                     f"{prefix}_prefill_token_share{brace} {share:g}"]
            for name, val in sorted(counters.items()):
                full = f"{prefix}_{name}_total"
                lines.append(f"# TYPE {full} counter")
                lines.append(f"{full}{brace} {val}")
            if self.tenant_tokens:
                full = f"{prefix}_tenant_tokens_total"
                lines.append(f"# TYPE {full} counter")
                tenant_extra = ("," + lbl) if lbl else ""
                for tenant, val in sorted(self.tenant_tokens.items()):
                    lines.append(
                        f'{full}{{tenant="{tenant}"{tenant_extra}}} {val}')
            for name, val in sorted(gauges.items()):
                full = f"{prefix}_{name}"
                lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full}{brace} {val:g}")
            extra = ("," + lbl) if lbl else ""
            for fam, label_key in LABELED_GAUGE_FAMILIES.items():
                vals = self.labeled_gauges.get(fam)
                if not vals:
                    continue
                full = f"{prefix}_{fam}"
                lines.append(f"# TYPE {full} gauge")
                for label, v in sorted(vals.items()):
                    # exposition label-value escaping (SLO.name also
                    # validates, but detector kinds / future callers
                    # ride the same emitter): \ -> \\, " -> \", NL -> \n
                    esc = (str(label).replace("\\", "\\\\")
                           .replace('"', '\\"').replace("\n", "\\n"))
                    lines.append(
                        f'{full}{{{label_key}="{esc}"{extra}}} {v:g}')
            full = f"{prefix}_stage_seconds_total"
            lines.append(f"# TYPE {full} counter")
            stage_extra = ("," + lbl) if lbl else ""
            for name, val in sorted(stages.items()):
                lines.append(
                    f'{full}{{stage="{name}"{stage_extra}}} {val:g}')
            for name, h in hists.items():
                lines.extend(h.prometheus_lines(f"{prefix}_{name}",
                                                labels=lbl))
                # per-tenant series of the SAME family ride under the
                # global header (one # TYPE line per family — repeated
                # headers are invalid exposition), labeled tenant="i".
                # The histogram-attribute name derives from the
                # exposition name so promoting a family into
                # _TENANT_HISTS is one edit, not two.
                base = name.replace("_seconds", "_s")
                if base not in _TENANT_HISTS:
                    continue
                for tenant, fams in sorted(self.tenant_latency.items()):
                    th = fams.get(base)
                    if th is None or not th.count:
                        continue
                    tlbl = f'tenant="{tenant}"' + (("," + lbl) if lbl
                                                   else "")
                    lines.extend(th.prometheus_lines(
                        f"{prefix}_{name}", labels=tlbl, type_line=False))
        return "\n".join(lines) + "\n"
