"""In-process metric time-series store — the SLO sensor substrate.

The serving telemetry (``serving_telemetry.py``) answers "what is the
state NOW" (point-in-time gauges, cumulative counters, all-time latency
histograms); the flight recorder answers "why was THIS token slow"
(per-step causality). Neither answers the question a fleet controller
has to ask: "what has tenant 3's p99 TTFT been doing over the last 60
seconds, and how fast is its error budget burning?" — that needs the
metrics *over time*. This module is that layer: a fixed-size ring
time-series store the serve loop feeds every existing gauge value and
counter into, with windowed ``rate()``/``mean()``/``max()``/
``quantile()`` queries, a structured :class:`Alert` log (SLO burns and
live pathology detections land here), and a JSON export.

Design points (same discipline as the flight recorder):

* **O(1) append** — each series is a pre-allocated ring of
  ``(monotonic_t, value)`` pairs; recording a sample is two list
  assignments under one lock.
* **zero cost when not attached** — the server's off-path is a single
  detached-attribute check (``if self.metrics_store is not None``);
  nothing in the engine or the serve loop touches this module unless a
  store is attached.
* **monotonic stamps** — samples are stamped with ``time.monotonic()``
  (the serving stack's deadline clock), so windows survive wall-clock
  adjustments and compare directly against request deadlines.
* **labels are data, not schema** — a series is keyed by
  ``(name, sorted(labels))``; the per-tenant latency series
  (``ttft_s{tenant="3"}``) and per-replica fleet merges ride the same
  mechanism the telemetry's ``tenant_tokens`` uses.

Alert *kinds* ARE schema: every ``Alert.kind`` raised anywhere in the
tree must appear in :data:`ALERT_KINDS` — the PTL007 analysis pass
(``paddle_tpu.analysis.slo_names``) enforces it at lint time, exactly
like PTL005 enforces the telemetry names.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

__all__ = ["Alert", "ALERT_KINDS", "MetricsStore", "Series",
           "nearest_rank_quantile"]


def nearest_rank_quantile(values, q):
    """Nearest-rank q-quantile of a value list (0.0 when empty) — THE
    one copy of the rank rule (``ceil(q*n)``-th smallest), shared by
    :meth:`Series.quantile` and the SLO engine's ``evaluate_slo`` so
    the two can never disagree on the same data. The ceil form matters
    at integral ranks: the p99 of 100 samples is the 99th smallest —
    traffic with EXACTLY the 1% bad events a p99 budget allows must
    measure at the good value, not the one outlier. ``values`` may be
    unsorted."""
    if not values:
        return 0.0
    vals = sorted(values)
    rank = -(-q * len(vals) // 1)           # ceil without an import
    return vals[min(max(int(rank) - 1, 0), len(vals) - 1)]

#: every Alert.kind the tree may raise — the strict-name registry the
#: PTL007 pass checks call sites against. "slo_burn" is the SLO
#: engine's multi-window burn-rate alert; the rest are the live
#: pathology detectors' kinds (paddle_tpu/profiler/slo.py), one per
#: explain_tail cause family promoted from post-hoc to streaming.
ALERT_KINDS = (
    "slo_burn",
    "ramp_thrash",
    "host_sync_regression",
    "spec_acceptance_collapse",
    "adapter_swap_storm",
    "swap_stall",
)


@dataclasses.dataclass
class Alert:
    """One structured alert: raised by the SLO engine or a pathology
    detector, cleared when the condition recovers. ``labels``
    distinguishes instances of one kind (``{"slo": "victim_ttft"}``);
    an alert stays in the store's bounded log after clearing so a
    report can answer "did it fire during the run" post-hoc."""
    kind: str                       # one of ALERT_KINDS (PTL007-checked)
    message: str
    raised_t: float                 # time.monotonic() at raise
    severity: str = "warning"
    labels: dict = dataclasses.field(default_factory=dict)
    data: dict = dataclasses.field(default_factory=dict)
    cleared_t: float | None = None

    @property
    def active(self):
        return self.cleared_t is None

    def to_dict(self):
        return {"kind": self.kind, "message": self.message,
                "severity": self.severity,
                "labels": dict(self.labels), "data": dict(self.data),
                "raised_t": round(self.raised_t, 6),
                "cleared_t": (round(self.cleared_t, 6)
                              if self.cleared_t is not None else None),
                "active": self.active}


class Series:
    """One metric's fixed-size sample ring: ``(t, value)`` pairs, oldest
    evicted on wrap. Appends are O(1); windowed reads walk at most
    ``capacity`` samples (bounded, lock-held by the owning store)."""

    __slots__ = ("name", "labels", "capacity", "_t", "_v", "_n")

    def __init__(self, name, labels=(), capacity=1024):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.name = name
        self.labels = tuple(labels)      # sorted (key, value) pairs
        self.capacity = int(capacity)
        self._t = [0.0] * self.capacity
        self._v = [0.0] * self.capacity
        self._n = 0                      # total samples ever appended

    def append(self, t, v):
        i = self._n % self.capacity
        self._t[i] = t
        self._v[i] = v
        self._n += 1

    def __len__(self):
        return min(self._n, self.capacity)

    @property
    def total_samples(self):
        return self._n

    def samples(self, since=None):
        """Retained ``(t, value)`` pairs, oldest first, optionally only
        those with ``t >= since``."""
        lo = max(0, self._n - self.capacity)
        out = []
        for i in range(lo, self._n):
            t = self._t[i % self.capacity]
            if since is None or t >= since:
                out.append((t, self._v[i % self.capacity]))
        return out

    def last(self):
        """The newest ``(t, value)``, or None on an empty series."""
        if not self._n:
            return None
        i = (self._n - 1) % self.capacity
        return (self._t[i], self._v[i])

    # -- windowed queries ----------------------------------------------
    def values(self, window_s=None, now=None):
        since = None
        if window_s is not None:
            if now is None:
                now = time.monotonic()
            since = now - window_s
        return [v for _, v in self.samples(since)]

    def mean(self, window_s=None, now=None):
        vals = self.values(window_s, now)
        return sum(vals) / len(vals) if vals else 0.0

    def max(self, window_s=None, now=None):
        vals = self.values(window_s, now)
        return max(vals) if vals else 0.0

    def rate(self, window_s=None, now=None):
        """Per-second delta of a CUMULATIVE series over the window:
        ``(v_last - v_first) / (t_last - t_first)`` for the retained
        samples inside it. 0.0 with <2 samples or a non-increasing
        clock; negative deltas (a counter reset) clamp to 0.0."""
        pts = self.samples(None if window_s is None else
                           (now if now is not None else time.monotonic())
                           - window_s)
        if len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return 0.0
        return max(v1 - v0, 0.0) / (t1 - t0)

    def quantile(self, q, window_s=None, now=None):
        """Nearest-rank q-quantile of the retained samples in the
        window (sorts up to ``capacity`` values — bounded)."""
        return nearest_rank_quantile(self.values(window_s, now), q)

    def truncated_for(self, window_s, now=None):
        """True when the ring has WRAPPED and its oldest retained
        sample is newer than the window start — a windowed read over
        ``window_s`` silently sees less history than asked for (grow
        ``capacity`` or the feed interval)."""
        if self._n <= self.capacity:
            return False
        if now is None:
            now = time.monotonic()
        oldest = self._t[self._n % self.capacity]
        return oldest > now - window_s

    def snapshot(self, max_samples=64):
        """JSON-ready summary + newest ``max_samples`` raw samples."""
        pts = self.samples()
        tail = pts[-max_samples:] if max_samples else []
        vals = [v for _, v in pts]
        return {"name": self.name,
                "labels": {k: v for k, v in self.labels},
                "samples_retained": len(pts),
                "samples_total": self._n,
                "last": (round(pts[-1][1], 6) if pts else None),
                "mean": (round(sum(vals) / len(vals), 6) if vals else None),
                "max": (round(max(vals), 6) if vals else None),
                "tail": [[round(t, 6), round(v, 6)] for t, v in tail]}


def _label_key(labels):
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _merge_labels(labels, kw):
    """Compose the ``labels=``-dict and ``**kwargs`` spellings into one
    label dict — both are accepted everywhere so neither style can
    silently query a phantom series."""
    if not labels:
        return kw
    merged = dict(labels)
    merged.update(kw)
    return merged


class MetricsStore:
    """Thread-safe collection of :class:`Series` + the bounded alert
    log. Writers: the serve loop (gauge/counter feed, one throttled
    pass per loop iteration), the token hot path (latency samples), the
    SLO engine and the pathology detectors (alerts). Readers: any
    thread (``slo_report``, the router's fleet merge, tests)."""

    def __init__(self, capacity=4096, max_alerts=256):
        self.capacity = int(capacity)
        self.max_alerts = int(max_alerts)
        self._lock = threading.Lock()
        self._series: dict[tuple, Series] = {}
        self._alerts: list[Alert] = []

    # -- write side ----------------------------------------------------
    def observe(self, name, value, t=None, labels=None, **kw):
        """Append one sample to series ``name{labels}`` (created on
        first sighting). ``t`` defaults to ``time.monotonic()``.
        Labels compose from the ``labels`` dict AND keyword arguments
        (every query method accepts both spellings too, so a caller
        mirroring either style hits the same series)."""
        if t is None:
            t = time.monotonic()
        key = (name, _label_key(_merge_labels(labels, kw)))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = Series(name, key[1], self.capacity)
            s.append(t, float(value))

    def raise_alert(self, kind, message, severity="warning", labels=None,
                    data=None):
        """Raise (or refresh) an alert. Deduped on ``(kind, labels)``:
        an already-ACTIVE instance is returned with its ``data``
        refreshed rather than duplicated, so a condition that holds for
        a thousand evaluations is one log entry."""
        labels = dict(labels or {})
        with self._lock:
            for a in reversed(self._alerts):
                if a.kind == kind and a.labels == labels and a.active:
                    if data:
                        a.data.update(data)
                    a.message = message
                    return a
            alert = Alert(kind, message, time.monotonic(),
                          severity=severity, labels=labels,
                          data=dict(data or {}))
            self._alerts.append(alert)
            if len(self._alerts) > self.max_alerts:
                # evict oldest CLEARED first; never silently drop an
                # active alert while an inactive one survives
                for i, old in enumerate(self._alerts):
                    if not old.active:
                        del self._alerts[i]
                        break
                else:
                    del self._alerts[0]
            return alert

    def clear_alert(self, kind, labels=None):
        """Clear the active alert matching ``(kind, labels)``. Returns
        the cleared alert, or None when nothing was active."""
        labels = dict(labels or {})
        with self._lock:
            for a in reversed(self._alerts):
                if a.kind == kind and a.labels == labels and a.active:
                    a.cleared_t = time.monotonic()
                    return a
        return None

    # -- read side -----------------------------------------------------
    def series(self, name, labels=None, **kw):
        """The exact series ``name{labels}``, or None."""
        with self._lock:
            return self._series.get(
                (name, _label_key(_merge_labels(labels, kw))))

    def matching(self, name, labels=None):
        """Every series named ``name``; with ``labels``, only those
        carrying ALL the given label pairs (a subset match, so
        ``matching("ttft_s")`` aggregates across tenants)."""
        want = _label_key(labels or {})
        with self._lock:
            return [s for (n, _), s in self._series.items()
                    if n == name and set(want) <= set(s.labels)]

    def values(self, name, window_s=None, now=None, labels=None):
        """Windowed sample VALUES concatenated across every matching
        series — the SLO engine's read (and, fed multiple stores'
        results, the fleet-level evaluation)."""
        out = []
        for s in self.matching(name, labels):
            with self._lock:
                out.extend(s.values(window_s, now))
        return out

    def window_truncated(self, name, window_s, now=None, labels=None):
        """True when ANY matching series' ring wrapped inside the
        window — the windowed read saw less history than ``window_s``
        asked for. The SLO engine surfaces this per evaluation so a
        high-rate series cannot silently collapse the slow window into
        the fast one."""
        if now is None:
            now = time.monotonic()
        for s in self.matching(name, labels):
            with self._lock:
                if s.truncated_for(window_s, now):
                    return True
        return False

    def rate(self, name, window_s=None, now=None, labels=None, **kw):
        # Series reads hold the store lock (the ring is mutated by
        # concurrent observe() appends — an unlocked samples() walk can
        # see a torn oldest slot and silently return 0/garbage)
        key = (name, _label_key(_merge_labels(labels, kw)))
        with self._lock:
            s = self._series.get(key)
            return s.rate(window_s, now) if s is not None else 0.0

    def mean(self, name, window_s=None, now=None, labels=None, **kw):
        key = (name, _label_key(_merge_labels(labels, kw)))
        with self._lock:
            s = self._series.get(key)
            return s.mean(window_s, now) if s is not None else 0.0

    def max(self, name, window_s=None, now=None, labels=None, **kw):
        key = (name, _label_key(_merge_labels(labels, kw)))
        with self._lock:
            s = self._series.get(key)
            return s.max(window_s, now) if s is not None else 0.0

    def last(self, name, labels=None, **kw):
        key = (name, _label_key(_merge_labels(labels, kw)))
        with self._lock:
            s = self._series.get(key)
            pt = s.last() if s is not None else None
        return pt[1] if pt is not None else None

    def windowed_values(self, name, window_s, fast_window_s=None,
                        now=None, labels=None):
        """ONE locked walk per matching series serving the SLO
        engine's whole read: ``(slow_values, fast_values, truncated)``
        — the fast-window values are the tail of the slow window's
        samples and ring truncation falls out of the same pass, so an
        evaluation costs one ring walk instead of three (these walks
        hold the store lock the token hot path's appends contend on)."""
        if now is None:
            now = time.monotonic()
        slow, fast = [], []
        truncated = False
        fast_since = now - fast_window_s if fast_window_s is not None \
            else None
        want = set(_label_key(labels or {}))
        with self._lock:
            for (n, _), s in self._series.items():
                if n != name or not want <= set(s.labels):
                    continue
                for t, v in s.samples(now - window_s):
                    slow.append(v)
                    if fast_since is not None and t >= fast_since:
                        fast.append(v)
                truncated = truncated or s.truncated_for(window_s, now)
        return slow, fast, truncated

    def alerts(self, active_only=False, kind=None):
        with self._lock:
            return [a for a in self._alerts
                    if (not active_only or a.active)
                    and (kind is None or a.kind == kind)]

    def snapshot(self, max_samples=64):
        """JSON-ready dump: every series' summary + the alert log."""
        with self._lock:
            series = [s.snapshot(max_samples)
                      for _, s in sorted(self._series.items())]
            alerts = [a.to_dict() for a in self._alerts]
        return {"series": series, "alerts": alerts,
                "capacity": self.capacity}

    def export_json(self, path, max_samples=256):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.snapshot(max_samples), f, indent=1)
        return path
