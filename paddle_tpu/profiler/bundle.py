"""Debug-bundle pretty-printer — ``python -m paddle_tpu.profiler.bundle``.

Renders a black-box bundle (:mod:`paddle_tpu.profiler.black_box`) as a
terminal postmortem: incident header, server/engine state, the worst
inter-token gaps with their cause verdicts and trace ids, the alert
log, and the last value of every metric series. Stdlib-only — a bundle
scp'd off a dead replica reads anywhere Python runs.
"""
from __future__ import annotations

import argparse
import json
import sys

from .black_box import BUNDLE_SCHEMA

__all__ = ["load_bundle", "format_bundle", "main"]


def load_bundle(path):
    """Read + schema-check one bundle file; raises ValueError on a
    file that is not a debug bundle (wrong/missing schema tag)."""
    with open(path) as f:
        bundle = json.load(f)
    schema = bundle.get("schema") if isinstance(bundle, dict) else None
    if schema != BUNDLE_SCHEMA:
        raise ValueError(
            f"{path}: not a debug bundle (schema={schema!r}, "
            f"expected {BUNDLE_SCHEMA!r})")
    return bundle


def _fmt_labels(labels):
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) \
        + "}"


def format_bundle(bundle, max_gaps=10, max_series=24):
    """The bundle as printable lines (list of str)."""
    lines = []
    add = lines.append
    add(f"== debug bundle ({bundle['schema']}) ==")
    add(f"reason: {bundle['reason']}"
        + (f" — {bundle['detail']}" if bundle.get("detail") else ""))
    add(f"pid: {bundle.get('pid')}   monotonic_t: "
        f"{bundle.get('monotonic_t')}")
    if bundle.get("truncated"):
        add("NOTE: tails truncated to fit the byte bound")
    srv = bundle.get("server")
    if srv:
        add("")
        add(f"-- server (replica {srv.get('replica')}) --")
        add(f"restarts: {srv.get('restarts')}   outstanding: "
            f"{srv.get('outstanding')}   queue_depth: "
            f"{srv.get('queue_depth')}")
        health = srv.get("health") or {}
        if isinstance(health, dict):
            add("health: " + ", ".join(
                f"{k}={health[k]}" for k in sorted(health)))
    faults = bundle.get("faults")
    if faults:
        add("")
        if isinstance(faults, dict):    # FaultInjector.snapshot() form
            fired = faults.get("fired") or []
            add(f"-- injected faults ({len(fired)} fired, "
                f"{len(faults.get('pending') or [])} pending"
                + (", HANGING" if faults.get("hanging") else "") + ") --")
            for f in fired:
                add(f"  {f}")
        else:
            add(f"-- injected faults ({len(faults)} fired) --")
            for f in faults:
                add(f"  {f}")
    eng = bundle.get("engine")
    if eng:
        add("")
        add("-- engine --")
        cfg = {k: v for k, v in eng.items()
               if k not in ("stats", "pool", "resident_rids", "waiting")}
        add("config: " + ", ".join(f"{k}={cfg[k]}" for k in sorted(cfg)))
        if "resident_rids" in eng:
            add(f"resident: {eng['resident_rids']}   waiting: "
                f"{eng.get('waiting')}")
        pool = eng.get("pool")
        if pool:
            add("pool: " + ", ".join(
                f"{k}={pool[k]}" for k in sorted(pool)))
        stats = eng.get("stats")
        if stats:
            add("stats: " + ", ".join(
                f"{k}={stats[k]}" for k in sorted(stats)))
    fr = bundle.get("flight_recorder")
    if fr:
        add("")
        add("-- flight recorder --")
        snap = fr.get("snapshot") or {}
        add(f"steps: {snap.get('steps_recorded')} retained / "
            f"{snap.get('steps_total')} total   requests: "
            f"{snap.get('requests_tracked')}")
        causes = snap.get("tail_causes_p99")
        if causes:
            add("tail causes: " + ", ".join(
                f"{k}={causes[k]}" for k in sorted(causes)))
        tail = fr.get("explain_tail") or []
        if tail:
            add(f"worst gaps (top {min(len(tail), max_gaps)}):")
            for e in tail[:max_gaps]:
                tid = e.get("trace_id")
                add(f"  req {e['request_id']}"
                    + (f" [{tid}]" if tid else "")
                    + f"  gap {e['gap_s'] * 1e3:.2f} ms"
                    f"  step {e.get('step_id')}  cause {e['cause']}")
        add(f"ring tail: {len(fr.get('ring_tail') or [])} StepRecords "
            f"(see JSON for per-step facts)")
    ms = bundle.get("metrics")
    if ms:
        add("")
        add("-- metrics store --")
        alerts = ms.get("alerts") or []
        if alerts:
            add(f"alerts ({len(alerts)}):")
            for a in alerts:
                state = "ACTIVE" if a.get("cleared_t") is None \
                    else "cleared"
                add(f"  [{state}] {a.get('kind')}"
                    f"{_fmt_labels(a.get('labels'))}: {a.get('message')}")
        series = ms.get("series") or []
        if series:
            add(f"series ({len(series)}, showing "
                f"{min(len(series), max_series)}):")
            for s in sorted(series,
                            key=lambda s: (s.get("name"),
                                           sorted((s.get("labels") or {})
                                                  .items())))[:max_series]:
                add(f"  {s.get('name')}{_fmt_labels(s.get('labels'))}: "
                    f"last={s.get('last')} mean={s.get('mean')} "
                    f"max={s.get('max')} "
                    f"n={s.get('samples_total')}")
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.profiler.bundle",
        description="Pretty-print a paddle_tpu debug bundle.")
    ap.add_argument("path", nargs="+", help="bundle JSON file(s)")
    ap.add_argument("--gaps", type=int, default=10,
                    help="worst inter-token gaps to show (default 10)")
    ap.add_argument("--series", type=int, default=24,
                    help="metric series to show (default 24)")
    args = ap.parse_args(argv)
    status = 0
    for i, path in enumerate(args.path):
        if i:
            print()
        try:
            bundle = load_bundle(path)
        except (OSError, ValueError, json.JSONDecodeError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            status = 1
            continue
        print("\n".join(format_bundle(
            bundle, max_gaps=args.gaps, max_series=args.series)))
    return status


if __name__ == "__main__":
    sys.exit(main())
