"""SLO engine + live pathology detectors over the metrics store.

Two halves of the sensor layer ROADMAP item 4's fleet controller will
close its loop against:

* **SLO engine** — declarative latency objectives
  (``SLO(metric="ttft_p99", tenant=3, target_s=0.25, window_s=60)``)
  evaluated from the :class:`~paddle_tpu.profiler.metrics_store
  .MetricsStore`'s windowed latency samples with Google-SRE-style
  MULTI-WINDOW burn-rate alerting: the error budget is ``1 -
  objective`` (a p99 target budgets 1% bad events), the burn rate of a
  window is ``bad_fraction / budget`` (1.0 = burning exactly the
  budget), and the alert condition requires the FAST window (recent,
  catches the onset and clears quickly on recovery) AND the SLOW
  window (sustained, immune to one bad sample) to both burn past the
  threshold — the standard trade that keeps pages fast without
  flapping on blips. Results surface as ``slo_report()`` (JSON +
  human text) and as the ``slo_burn_rate{slo=...}`` /
  ``slo_breached{slo=...}`` telemetry gauges.
* **pathology detectors** — the ``explain_tail`` cause taxonomy
  promoted from post-hoc to STREAMING: each detector subscribes to the
  flight recorder's completed StepRecords
  (:meth:`FlightRecorder.subscribe`) and watches a bounded window of
  recent steps for its shape — ramp-thrash (preempt/admit churn with
  zero committed decode progress), host-sync regression (sync share of
  stride-1 step wall above budget), speculative-acceptance collapse,
  adapter-swap storm, swap-stall dominance. A firing detector raises a
  structured :class:`~paddle_tpu.profiler.metrics_store.Alert` into
  the store and flips the ``pathology_active{kind=...}`` gauge; it
  clears both when the window recovers.

Every metric family and alert kind here is STRICT-NAMED: the PTL007
pass (``paddle_tpu.analysis.slo_names``) checks detector kinds and
``set_labeled_gauge`` call sites against the
:data:`~paddle_tpu.profiler.metrics_store.ALERT_KINDS` /
``LABELED_GAUGE_FAMILIES`` registries at lint time.
"""
from __future__ import annotations

import collections
import dataclasses
import re
import threading
import time

from .metrics_store import nearest_rank_quantile as _quantile

__all__ = ["SLO", "SLOEngine", "evaluate_slo", "format_slo_report",
           "format_fleet_report", "default_detectors",
           "RampThrashDetector", "HostSyncRegressionDetector",
           "SpecCollapseDetector", "AdapterSwapStormDetector",
           "SwapStallDetector", "SLO_METRIC_BASES"]

#: latency families an SLO metric may target — each maps to the store
#: series the server feeds (``<base>_s``, labeled ``tenant="i"``) and
#: to the per-tenant telemetry histograms of the same name.
SLO_METRIC_BASES = ("ttft", "inter_token", "e2e", "queue_wait")

_METRIC_RE = re.compile(
    r"^(?P<base>" + "|".join(SLO_METRIC_BASES) + r")_p(?P<pct>\d{2})$")


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative latency objective.

    ``metric``: ``"<base>_p<NN>"`` with base one of
    :data:`SLO_METRIC_BASES` — e.g. ``"ttft_p99"`` reads "the p99 of
    TTFT must stay under ``target_s``". ``tenant``: an adapter id to
    scope the objective to one tenant's traffic (None = all traffic).
    ``window_s`` is the SLOW alert window; ``fast_window_s`` defaults
    to ``window_s / 12`` (the SRE workbook's 1h:5m ratio).
    ``burn_threshold``: both windows must burn at this multiple of the
    error budget before the alert fires (1.0 = burning exactly the
    budget; the default 6.0 pages on a budget that would exhaust in
    window/6)."""
    name: str
    metric: str = "ttft_p99"
    target_s: float = 1.0
    tenant: int | None = None
    window_s: float = 60.0
    fast_window_s: float | None = None
    burn_threshold: float = 6.0

    def __post_init__(self):
        if not re.fullmatch(r"[A-Za-z0-9_.:\- ]+", self.name or ""):
            # the name becomes a Prometheus label VALUE — quotes,
            # backslashes or newlines would corrupt the exposition a
            # whole fleet scrape hangs off
            raise ValueError(
                f"SLO name must be non-empty [A-Za-z0-9_.:- ] "
                f"(it is exported as a label value), got {self.name!r}")
        if _METRIC_RE.match(self.metric) is None:
            raise ValueError(
                f"SLO metric must be '<base>_p<NN>' with base in "
                f"{SLO_METRIC_BASES}, got {self.metric!r}")
        if not self.target_s > 0:
            raise ValueError(f"target_s must be > 0, got {self.target_s}")
        if not self.window_s > 0:
            raise ValueError(f"window_s must be > 0, got {self.window_s}")

    @property
    def metric_base(self):
        return _METRIC_RE.match(self.metric).group("base")

    @property
    def objective(self):
        """The quantile as a fraction: p99 -> 0.99."""
        return int(_METRIC_RE.match(self.metric).group("pct")) / 100.0

    @property
    def fast_window(self):
        return self.fast_window_s if self.fast_window_s is not None \
            else self.window_s / 12.0

    @property
    def series_name(self):
        return self.metric_base + "_s"

    @property
    def series_labels(self):
        return {"tenant": str(self.tenant)} \
            if self.tenant is not None else None


def _burn(values, target_s, budget):
    """Burn rate of one window: bad_fraction / error_budget. 0.0 on an
    empty window (no evidence is not evidence of burning)."""
    if not values:
        return 0.0
    bad = sum(1 for v in values if v > target_s)
    return (bad / len(values)) / max(budget, 1e-9)


def evaluate_slo(slo, fast_values, slow_values, window_truncated=False):
    """THE one copy of the burn-rate math — shared by the per-server
    :class:`SLOEngine` and the router's fleet-level evaluation (which
    feeds it windowed samples concatenated across replica stores).
    ``window_truncated``: the caller's store reported that a ring
    wrapped INSIDE the slow window — the evaluation then saw less
    history than ``window_s`` asked for (surfaced on the result so a
    high-rate series cannot silently collapse the slow window's
    blip-immunity into the fast window's reactivity; grow the store
    capacity when it shows)."""
    budget = 1.0 - slo.objective
    bf = _burn(fast_values, slo.target_s, budget)
    bs = _burn(slow_values, slo.target_s, budget)
    measured = _quantile(slow_values, slo.objective)
    return {
        "window_truncated": bool(window_truncated),
        "slo": slo.name, "metric": slo.metric, "tenant": slo.tenant,
        "target_s": slo.target_s, "objective": slo.objective,
        "window_s": slo.window_s, "fast_window_s": slo.fast_window,
        "samples_slow": len(slow_values), "samples_fast": len(fast_values),
        "measured_s": round(measured, 6),
        #: the objective itself, over the slow window
        "breached": bool(slow_values) and measured > slo.target_s,
        "burn_rate_fast": round(bf, 4), "burn_rate_slow": round(bs, 4),
        "burn_threshold": slo.burn_threshold,
        #: the multi-window ALERT condition: fast AND slow both burning
        #: (epsilon absorbs the 1-0.99 float representation error so a
        #: burn of exactly-threshold compares true)
        "burning": (bf >= slo.burn_threshold - 1e-9
                    and bs >= slo.burn_threshold - 1e-9),
    }


def format_slo_report(report):
    """Human text for one server's ``slo_report()`` dict."""
    lines = []
    for r in report.get("slos", ()):
        tenant = f" tenant={r['tenant']}" if r["tenant"] is not None else ""
        state = "BURNING" if r["burning"] else (
            "breached" if r["breached"] else "ok")
        lines.append(
            f"[{state:>8}] {r['slo']}: {r['metric']}{tenant} = "
            f"{r['measured_s'] * 1e3:.1f}ms (target "
            f"{r['target_s'] * 1e3:.1f}ms) burn fast/slow = "
            f"{r['burn_rate_fast']:.1f}/{r['burn_rate_slow']:.1f} "
            f"(threshold {r['burn_threshold']:.1f}, "
            f"n={r['samples_slow']})")
    active = [a for a in report.get("alerts", ()) if a["active"]]
    for a in active:
        lines.append(f"[   ALERT] {a['kind']} {a['labels']}: "
                     f"{a['message']}")
    for kind, on in sorted(report.get("pathologies", {}).items()):
        if on:
            lines.append(f"[PATHOLOGY] {kind} active")
    if not lines:
        lines.append("[      ok] no SLOs configured / nothing burning")
    return "\n".join(lines)


def format_fleet_report(report):
    """Human text for ``ReplicaRouter.slo_report()``."""
    lines = ["fleet:"]
    fleet = report.get("fleet", {})
    lines.append(format_slo_report(
        {"slos": fleet.get("slos", ()), "alerts": fleet.get("alerts", ()),
         "pathologies": {}}))
    for kind, reps in sorted(fleet.get("pathologies", {}).items()):
        lines.append(f"[PATHOLOGY] {kind} active on replicas {reps}")
    for t, fams in sorted(fleet.get("tenant_latency", {}).items()):
        ttft = fams.get("ttft", {})
        if ttft.get("count"):
            lines.append(
                f"tenant {t}: ttft p99 {ttft['p99_s'] * 1e3:.1f}ms "
                f"p50 {ttft['p50_s'] * 1e3:.1f}ms (n={ttft['count']})")
    return "\n".join(lines)


class SLOEngine:
    """Evaluates a list of :class:`SLO`\\ s against one store,
    maintaining the ``slo_burn_rate``/``slo_breached`` labeled gauges
    and the ``slo_burn`` alert per objective. Cheap enough to run on a
    throttled serve-loop cadence: each evaluation walks at most
    ``capacity`` ring samples per (SLO, window)."""

    def __init__(self, slos, store, telemetry=None):
        self.slos = list(slos)
        for s in self.slos:
            if not isinstance(s, SLO):
                raise TypeError(f"expected SLO, got {type(s).__name__}")
        self.store = store
        self.telemetry = telemetry
        #: serializes evaluations: the serve loop's throttled pass and
        #: any-thread slo_report() callers both evaluate — unserialized,
        #: a delayed raise off stale windows could land AFTER the clear
        #: a fresher evaluation just published
        self._lock = threading.Lock()

    def add(self, slo):
        """Append an objective at runtime (benches calibrate a target
        from a warmup phase, then arm the SLO)."""
        if not isinstance(slo, SLO):
            raise TypeError(f"expected SLO, got {type(slo).__name__}")
        with self._lock:
            self.slos.append(slo)
        return slo

    def evaluate(self, now=None):
        """Evaluate every SLO; updates gauges + alerts; returns the
        per-SLO result dicts (see :func:`evaluate_slo`). Serialized —
        concurrent callers (loop pass + slo_report) evaluate one at a
        time so alert raise/clear transitions stay ordered by window
        freshness."""
        with self._lock:
            return self._evaluate_locked(now)

    def _evaluate_locked(self, now):
        if now is None:
            now = time.monotonic()
        out = []
        tel = self.telemetry
        for s in list(self.slos):
            slow, fast, truncated = self.store.windowed_values(
                s.series_name, s.window_s, fast_window_s=s.fast_window,
                now=now, labels=s.series_labels)
            r = evaluate_slo(s, fast, slow, window_truncated=truncated)
            out.append(r)
            if tel is not None:
                tel.set_labeled_gauge("slo_burn_rate", s.name,
                                      r["burn_rate_fast"])
                tel.set_labeled_gauge("slo_breached", s.name,
                                      1.0 if r["burning"] else 0.0)
            if r["burning"]:
                self.store.raise_alert(
                    "slo_burn",
                    f"{s.name}: {s.metric} burn fast/slow "
                    f"{r['burn_rate_fast']:.1f}/{r['burn_rate_slow']:.1f} "
                    f">= {s.burn_threshold:.1f} "
                    f"(measured {r['measured_s'] * 1e3:.1f}ms, target "
                    f"{s.target_s * 1e3:.1f}ms)",
                    labels={"slo": s.name}, data=r)
            else:
                self.store.clear_alert("slo_burn", labels={"slo": s.name})
        return out


# ---------------------------------------------------------------------------
# live pathology detectors — explain_tail's taxonomy, streaming
# ---------------------------------------------------------------------------

class _StepWindowDetector:
    """Base: keep the last ``window`` completed StepRecords, evaluate a
    shape predicate after each, raise/clear the alert + the
    ``pathology_active`` gauge on edge transitions. ``on_step`` runs on
    the engine thread (the recorder's subscriber callback) — state is
    single-writer; ``active`` reads are racy-but-monotonic booleans."""

    kind = "unnamed"
    min_steps = 8

    def __init__(self, store, telemetry=None, window=32, min_steps=None):
        self.store = store
        self.telemetry = telemetry
        self._recs = collections.deque(maxlen=int(window))
        if min_steps is not None:
            self.min_steps = int(min_steps)
        self.active = False
        self.fired = 0          # raise edges this lifetime

    # subclasses: (fire: bool, data: dict) over the current window
    def _evaluate(self, recs):
        raise NotImplementedError

    def _message(self, data):
        return f"{self.kind}: {data}"

    def on_step(self, rec):
        self._recs.append(rec)
        recs = tuple(self._recs)
        if len(recs) < self.min_steps:
            return
        fire, data = self._evaluate(recs)
        if fire and not self.active:
            self.active = True
            self.fired += 1
            self.store.raise_alert(self.kind, self._message(data),
                                   data=data)
            if self.telemetry is not None:
                self.telemetry.set_labeled_gauge("pathology_active",
                                                 self.kind, 1.0)
        elif self.active and not fire:
            self.active = False
            self.store.clear_alert(self.kind)
            if self.telemetry is not None:
                self.telemetry.set_labeled_gauge("pathology_active",
                                                 self.kind, 0.0)

    def reset(self):
        """Drop the step window AND clear any active alert/gauge — the
        server calls this at start() so a restarted serve never
        evaluates a window mixing two runs' records, and an alert that
        was active at stop() does not outlive the loop it described."""
        self._recs.clear()
        if self.active:
            self.active = False
            self.store.clear_alert(self.kind)
            if self.telemetry is not None:
                self.telemetry.set_labeled_gauge("pathology_active",
                                                 self.kind, 0.0)


def _decode_tokens(rec):
    return sum(n for _, _, kind, n in rec.grants
               if kind in ("decode", "verify"))


class RampThrashDetector(_StepWindowDetector):
    """Preemption/admission churn with NO committed decode progress —
    the livelock shape the PR-13 admission-defer guarantee fixed for
    ramp-vs-ramp, still reachable under adversarial churn. Fires when
    the window carries ``min_preemptions`` preemption events while not
    one decode/verify token was granted."""

    kind = "ramp_thrash"
    min_steps = 6

    def __init__(self, store, telemetry=None, window=32, min_steps=None,
                 min_preemptions=3):
        super().__init__(store, telemetry, window, min_steps)
        self.min_preemptions = int(min_preemptions)

    def _evaluate(self, recs):
        preempts = sum(len(r.preemptions) for r in recs)
        decode = sum(_decode_tokens(r) for r in recs)
        data = {"preemptions": preempts, "decode_tokens": decode,
                "steps": len(recs)}
        return (preempts >= self.min_preemptions and decode == 0), data

    def _message(self, data):
        return (f"ramp thrash: {data['preemptions']} preemptions over "
                f"{data['steps']} steps with zero committed decode "
                f"tokens — admissions are churning each other out")


class HostSyncRegressionDetector(_StepWindowDetector):
    """Host-sync share of STRIDE-1 step wall above budget, sustained.
    Amortized readouts (``readout_stride > 1``) are excluded — a
    sync-dominated stride step is ``batched_readout`` working as
    designed, exactly like the explain_tail split."""

    kind = "host_sync_regression"
    min_steps = 8

    def __init__(self, store, telemetry=None, window=32, min_steps=None,
                 budget=0.5):
        super().__init__(store, telemetry, window, min_steps)
        self.budget = float(budget)

    def _evaluate(self, recs):
        ones = [r for r in recs if r.readout_stride == 1 and r.t_finish]
        wall = sum(r.wall_s for r in ones)
        sync = sum(r.sync_s for r in ones)
        share = sync / wall if wall > 0 else 0.0
        data = {"sync_share": round(share, 4), "budget": self.budget,
                "stride1_steps": len(ones)}
        return (len(ones) >= self.min_steps
                and share > self.budget), data

    def _message(self, data):
        return (f"host-sync regression: token syncs are "
                f"{data['sync_share']:.0%} of stride-1 step wall "
                f"(budget {data['budget']:.0%}) — raise readout_stride "
                f"or chase the transfer path")


class SpecCollapseDetector(_StepWindowDetector):
    """Speculative draft acceptance collapsed: the window verified at
    least ``min_proposed`` drafts and committed under ``min_rate`` of
    them — verify windows are burning compute on tokens that roll
    back (the adaptive-k EWMA should already be shrinking k; sustained
    collapse means the drafter does not fit the workload)."""

    kind = "spec_acceptance_collapse"
    min_steps = 4

    def __init__(self, store, telemetry=None, window=32, min_steps=None,
                 min_proposed=16, min_rate=0.2):
        super().__init__(store, telemetry, window, min_steps)
        self.min_proposed = int(min_proposed)
        self.min_rate = float(min_rate)

    def _evaluate(self, recs):
        acc = sum(r.spec_accepted for r in recs)
        rej = sum(r.spec_rejected for r in recs)
        total = acc + rej
        rate = acc / total if total else 1.0
        data = {"accepted": acc, "rejected": rej,
                "acceptance_rate": round(rate, 4)}
        return (total >= self.min_proposed and rate < self.min_rate), data

    def _message(self, data):
        return (f"speculative acceptance collapse: "
                f"{data['acceptance_rate']:.0%} of "
                f"{data['accepted'] + data['rejected']} drafts committed "
                f"(floor {self.min_rate:.0%})")


class AdapterSwapStormDetector(_StepWindowDetector):
    """Adapter device-cache swap-ins riding a large fraction of recent
    steps: the multi-tenant working set is larger than
    ``adapter_cache_slots`` and admissions are paying a host upload
    each — grow the cache or shard tenants across replicas."""

    kind = "adapter_swap_storm"
    min_steps = 8

    def __init__(self, store, telemetry=None, window=32, min_steps=None,
                 min_swaps=4, swap_share=0.5):
        super().__init__(store, telemetry, window, min_steps)
        self.min_swaps = int(min_swaps)
        self.swap_share = float(swap_share)

    def _evaluate(self, recs):
        swaps = sum(r.adapter_swaps for r in recs)
        share = swaps / len(recs)
        data = {"adapter_swaps": swaps, "steps": len(recs),
                "swaps_per_step": round(share, 4)}
        return (swaps >= self.min_swaps
                and share >= self.swap_share), data

    def _message(self, data):
        return (f"adapter swap storm: {data['adapter_swaps']} swap-ins "
                f"over {data['steps']} steps "
                f"({data['swaps_per_step']:.2f}/step) — working set "
                f"exceeds the adapter cache")


class SwapStallDetector(_StepWindowDetector):
    """KV host-tier swap traffic on a dominant share of recent steps:
    preemption pressure is converting into device<->host copies every
    few steps — the pool is undersized for the resident set even WITH
    the cheap eviction path (grow the pool, or shed admissions)."""

    kind = "swap_stall"
    min_steps = 8

    def __init__(self, store, telemetry=None, window=32, min_steps=None,
                 min_swap_steps=3, swap_share=0.25):
        super().__init__(store, telemetry, window, min_steps)
        self.min_swap_steps = int(min_swap_steps)
        self.swap_share = float(swap_share)

    def _evaluate(self, recs):
        swapping = [r for r in recs
                    if (r.kv_swap_in_bytes or 0) + (r.kv_swap_out_bytes
                                                    or 0) > 0]
        share = len(swapping) / len(recs)
        byts = sum((r.kv_swap_in_bytes or 0) + (r.kv_swap_out_bytes or 0)
                   for r in swapping)
        data = {"swap_steps": len(swapping), "steps": len(recs),
                "swap_step_share": round(share, 4), "swap_bytes": byts}
        return (len(swapping) >= self.min_swap_steps
                and share >= self.swap_share), data

    def _message(self, data):
        return (f"swap-stall dominance: host-tier traffic on "
                f"{data['swap_steps']}/{data['steps']} recent steps "
                f"({data['swap_bytes']} bytes) — the pool is undersized "
                f"for the resident set")


def default_detectors(store, telemetry=None):
    """The standard detector set the server arms when a metrics store
    AND a flight recorder are both attached."""
    return [RampThrashDetector(store, telemetry),
            HostSyncRegressionDetector(store, telemetry),
            SpecCollapseDetector(store, telemetry),
            AdapterSwapStormDetector(store, telemetry),
            SwapStallDetector(store, telemetry)]
