"""paddle.hub — load models from local repo directories or github.

Reference: python/paddle/hub.py (list/help/load over a hubconf.py contract).
Zero-egress environment: the 'github' source raises; local directories work
exactly like the reference ('<path>' containing hubconf.py with callables and
an optional `dependencies` list).
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load", "load_state_dict_from_url"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise RuntimeError(f"no {_HUBCONF} found in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    deps = getattr(mod, "dependencies", [])
    missing = [d for d in deps if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(f"Missing dependencies: {missing}")
    return mod


def _resolve(repo_dir, source):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            f"Unknown source: {source}. Allowed values: 'github' | 'gitee' | "
            "'local'.")
    if source != "local":
        raise RuntimeError(
            f"source='{source}' needs network access, which this environment "
            "does not have; clone the repo and use source='local'")
    return repo_dir


def list(repo_dir, source="github", force_reload=False):
    mod = _load_hubconf(_resolve(repo_dir, source))
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise RuntimeError(f"model {model} not found in hubconf")
    return fn.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    mod = _load_hubconf(_resolve(repo_dir, source))
    fn = getattr(mod, model, None)
    if fn is None:
        raise RuntimeError(f"model {model} not found in hubconf")
    return fn(**kwargs)


def load_state_dict_from_url(url, model_dir=None, check_hash=False,
                             file_name=None, map_location=None):
    raise RuntimeError("load_state_dict_from_url needs network access; "
                       "download the weights and use paddle.load instead")
