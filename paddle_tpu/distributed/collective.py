"""Collective communication API.

Reference: python/paddle/distributed/communication/* (one module per op) over
ProcessGroupNCCL. TPU-native split (SURVEY §5.8):
- Device collectives are COMPILED programs: the eager API below operates on
  DistTensors (mesh-placed jax.Arrays) and lowers each op to a reshard whose
  XLA lowering IS the collective (p->r = all_reduce, s->r = all_gather,
  p->s = reduce_scatter, s->s' = all_to_all).
- `paddle_tpu.distributed.functional` exposes the in-graph primitives
  (psum/all_gather/ppermute/all_to_all) for shard_map-authored parallel code —
  what fleet TP/PP/ring-attention use.
- Host-side object collectives ride the TCPStore (Gloo analog) for multi-process
  coordination.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from .mesh import ProcessMesh, Shard, Replicate, Partial
from .api import is_dist_tensor, reshard, shard_tensor, full_value, DistMeta
from .env import Group, get_world_size, global_rank


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _axis_of(tensor, group):
    """Resolve which mesh axis a collective runs over."""
    if group is not None and group.axis is not None and group.mesh is not None:
        return group.mesh, group.axis
    if is_dist_tensor(tensor):
        meta = tensor._dist_meta
        # default to the first axis with a non-replicate placement, else axis 0
        for i, p in enumerate(meta.placements):
            if not p.is_replicate():
                return meta.mesh, meta.mesh.dim_names[i]
        return meta.mesh, meta.mesh.dim_names[0]
    return None, None


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Partial -> Replicate over the group axis (compiles to one all_reduce)."""
    if not is_dist_tensor(tensor):
        return tensor  # single logical copy: already reduced in global view
    mesh, axis = _axis_of(tensor, group)
    ax_idx = mesh.dim_names.index(axis)
    placements = list(tensor._dist_meta.placements)
    if not placements[ax_idx].is_partial():
        return tensor
    if op == ReduceOp.AVG:
        out = reshard(tensor, mesh, [Replicate() if i == ax_idx else p
                                     for i, p in enumerate(placements)])
        res = dispatch(lambda v: v / mesh.shape[ax_idx], (out,), {}, name="avg")
        res._dist_meta = out._dist_meta
        tensor._value = res._value
        tensor._dist_meta = res._dist_meta
        return tensor
    placements[ax_idx] = Replicate()
    out = reshard(tensor, mesh, placements)
    tensor._value = out._value
    tensor._dist_meta = out._dist_meta
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    """Shard(d) -> Replicate; fills tensor_list with per-rank slices."""
    if not is_dist_tensor(tensor):
        n = group.nranks if group is not None else 1
        tensor_list.extend([tensor for _ in range(n)])
        return
    mesh, axis = _axis_of(tensor, group)
    ax_idx = mesh.dim_names.index(axis)
    placements = list(tensor._dist_meta.placements)
    p = placements[ax_idx]
    placements[ax_idx] = Replicate()
    out = reshard(tensor, mesh, placements)
    n = mesh.shape[ax_idx]
    if p.is_shard():
        d = p.get_dim()
        chunks = jnp.split(out._value, n, axis=d)
        tensor_list.extend([Tensor(c) for c in chunks])
    else:
        tensor_list.extend([Tensor(out._value) for _ in range(n)])


def all_gather_object(object_list, obj, group=None):
    """Host-side gather over processes via TCPStore (Gloo analog)."""
    world = get_world_size()
    if world == 1:
        object_list.append(obj)
        return
    from .store import create_or_get_global_tcp_store
    store = create_or_get_global_tcp_store()
    rank = global_rank()
    store.set(f"__ag/{rank}", obj)
    store.barrier("all_gather_object", world_size=world)
    for r in range(world):
        object_list.append(store.wait(f"__ag/{r}"))


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Global-view broadcast: value of logical rank src becomes everyone's value.
    For DistTensors this is reshard-to-Replicate."""
    if is_dist_tensor(tensor):
        mesh = tensor._dist_meta.mesh
        out = reshard(tensor, mesh, [Replicate()] * mesh.ndim)
        tensor._value = out._value
        tensor._dist_meta = out._dist_meta
    return tensor


def broadcast_object_list(object_list, src=0, group=None):
    world = get_world_size()
    if world == 1:
        return
    from .store import create_or_get_global_tcp_store
    store = create_or_get_global_tcp_store()
    if global_rank() == src:
        store.set("__bcast", object_list)
    received = store.wait("__bcast")
    object_list.clear()
    object_list.extend(received)
    store.barrier("bcast_done", world_size=world)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group)


def reduce_scatter(tensor_out, tensor_list_or_tensor, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """Partial -> Shard(0): one reduce_scatter in XLA."""
    t = tensor_list_or_tensor
    if isinstance(t, (list, tuple)):
        stacked = dispatch(lambda *vs: jnp.concatenate(vs, axis=0), tuple(t), {},
                           name="concat")
        t = stacked
    if not is_dist_tensor(t):
        tensor_out._value = t._value
        return tensor_out
    mesh, axis = _axis_of(t, group)
    ax_idx = mesh.dim_names.index(axis)
    placements = list(t._dist_meta.placements)
    placements[ax_idx] = Shard(0)
    out = reshard(t, mesh, placements)
    tensor_out._value = out._value
    tensor_out._dist_meta = out._dist_meta
    return tensor_out


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """Shard(d0) -> Shard(d1) transposition (XLA all_to_all) on stacked input."""
    stacked = dispatch(lambda *vs: jnp.stack(vs, axis=0), tuple(in_tensor_list), {},
                       name="stack")
    n = len(in_tensor_list)
    # global view: out[j] = in[j] chunk-swapped; single-controller = transpose chunks
    chunks = jnp.split(stacked._value, n, axis=1) if stacked._value.ndim > 1 else None
    for j in range(n):
        if chunks is not None:
            out_tensor_list.append(Tensor(jnp.concatenate(
                [jnp.split(in_tensor_list[i]._value, n, axis=0)[j]
                 for i in range(n)], axis=0)))
        else:
            out_tensor_list.append(in_tensor_list[j])
    return out_tensor_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        tensor._value = tensor_list[global_rank() if get_world_size() > 1 else 0]._value
    return tensor


def send(tensor, dst=0, group=None, sync_op=True):
    raise RuntimeError(
        "eager send/recv across compiled-collective ranks is not meaningful on a "
        "single controller; use fleet pipeline parallel (ppermute) or "
        "distributed.functional inside shard_map")


def recv(tensor, src=0, group=None, sync_op=True):
    raise RuntimeError(
        "eager send/recv across compiled-collective ranks is not meaningful on a "
        "single controller; use fleet pipeline parallel (ppermute) or "
        "distributed.functional inside shard_map")


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


@dataclass
class P2POp:
    op: object
    tensor: object
    peer: int
    group: object = None


def batch_isend_irecv(p2p_op_list):
    raise RuntimeError("use fleet pipeline parallel for p2p schedules on TPU")


def stream_all_reduce(*a, **k):  # communication.stream.* parity aliases
    return all_reduce(*a, **k)


# ---------------------------------------------------------------------------
# In-graph functional collectives (for shard_map-authored parallel code)
# ---------------------------------------------------------------------------

class functional:
    """lax collectives under their paddle-ish names; use inside shard_map bodies."""

    @staticmethod
    def all_reduce(x, axis_name, op=ReduceOp.SUM):
        if op == ReduceOp.SUM:
            return jax.lax.psum(x, axis_name)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(x, axis_name)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(x, axis_name)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(x, axis_name)
        raise ValueError(op)

    psum = staticmethod(jax.lax.psum)
    pmean = staticmethod(jax.lax.pmean)
    pmax = staticmethod(jax.lax.pmax)
    ppermute = staticmethod(jax.lax.ppermute)

    @staticmethod
    def all_gather(x, axis_name, axis=0, tiled=True):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)

    @staticmethod
    def reduce_scatter(x, axis_name, axis=0, tiled=True):
        return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=tiled)

    @staticmethod
    def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
        return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=tiled)

    @staticmethod
    def axis_index(axis_name):
        return jax.lax.axis_index(axis_name)

    @staticmethod
    def shift(x, axis_name, offset=1):
        """Ring shift by `offset` (pipeline/ring-attention building block)."""
        n = jax.lax.axis_size(axis_name)
        perm = [(i, (i + offset) % n) for i in range(n)]
        return jax.lax.ppermute(x, axis_name, perm)


# paddle-name aliases + the remaining eager collective surface ---------------

def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """reference: communication/all_to_all.py:26 — same contract as
    all_to_all (paddle exports both spellings)."""
    return all_to_all(out_tensor_list, in_tensor_list, group, sync_op)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """reference: communication/all_to_all.py:78. Global-view semantics match
    all_to_all above: the input's leading dim concatenates the n ranks'
    tensors (the way a Shard(0) DistTensor's global value does); each rank's
    chunk splits into n sends (in_split_sizes, even by default), and rank r's
    output concatenates sub-chunk r from every rank."""
    n = group.nranks if group is not None else max(1, get_world_size())
    v = in_tensor._value
    if n == 1:
        out_tensor._value = v
        return out_tensor
    if v.shape[0] % n:
        raise ValueError(
            f"alltoall_single input dim 0 ({v.shape[0]}) must divide the "
            f"group size {n}")
    k = v.shape[0] // n
    rank_chunks = [v[i * k:(i + 1) * k] for i in range(n)]

    def subsplit(chunk):
        if in_split_sizes is None:
            return jnp.split(chunk, n, axis=0)
        offs, subs = 0, []
        for s in in_split_sizes:
            subs.append(chunk[offs:offs + s])
            offs += s
        return subs

    subs = [subsplit(c) for c in rank_chunks]
    out_tensor._value = jnp.concatenate(
        [s for r in range(n) for s in (subs[i][r] for i in range(n))], axis=0)
    return out_tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """reference: communication/gather.py:29 — all_gather restricted to dst;
    in the single-controller global view every rank holds the gather."""
    if gather_list is None:
        gather_list = []
    all_gather(gather_list, tensor, group, sync_op)
    return None


def scatter_object_list(out_object_list, in_object_list, src=0, group=None):
    """Host-object scatter over the store (reference:
    communication/scatter.py scatter_object_list)."""
    world = get_world_size()
    if world == 1:
        out_object_list.append(in_object_list[0])
        return
    from .store import create_or_get_global_tcp_store
    store = create_or_get_global_tcp_store()
    rank = global_rank()
    if rank == src:
        for r in range(world):
            store.set(f"__so/{r}", in_object_list[r])
    store.barrier("scatter_object_list", world_size=world)
    out_object_list.append(store.wait(f"__so/{rank}"))


def wait(tensor, group=None, use_calc_stream=True):
    """reference: communication/wait.py — block until the tensor's pending
    collective lands (PJRT: block_until_ready)."""
    v = getattr(tensor, "_value", tensor)
    try:
        v.block_until_ready()
    except AttributeError:
        import numpy as _np
        _np.asarray(v)
    return tensor
