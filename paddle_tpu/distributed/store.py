"""TCPStore — socket KV rendezvous (reference: phi/core/distributed/store/
tcp_store.h:121, CreateOrGetGlobalTCPStore at store_utils.h:33).

Rank 0 hosts the store server; all ranks connect as clients. Used for
multi-process bootstrap metadata, barriers, and host-side object collectives
(the Gloo-analog for small host tensors/objects). Device-side collectives never
touch this — they compile to XLA ICI/DCN ops.

The server is the native C++ one (csrc/tcp_store.cc — GIL-free thread-per-conn
daemon, like the reference's MasterDaemon) when the runtime library is
available, with a pure-Python thread fallback speaking the identical binary
protocol (csrc/pt_native.h documents it).
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

_OP_SET, _OP_GET, _OP_WAIT, _OP_ADD, _OP_DEL, _OP_NUM = 1, 2, 3, 4, 5, 6
_TAG_BYTES, _TAG_I64 = 0, 1


def _recv_full(sock, n) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("store connection closed")
        buf += chunk
    return buf


class _PyStoreServer(threading.Thread):
    """Fallback server — same wire protocol as csrc/tcp_store.cc."""

    def __init__(self, host, port):
        super().__init__(daemon=True)
        self._kv: dict[str, tuple[int, bytes]] = {}
        self._cv = threading.Condition()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self.port = self._srv.getsockname()[1]
        self._srv.listen(128)

    def stop(self):
        try:
            self._srv.close()
        except OSError:
            pass

    def num_keys(self):
        with self._cv:
            return len(self._kv)

    def run(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                op = _recv_full(conn, 1)[0]
                (klen,) = struct.unpack("!I", _recv_full(conn, 4))
                key = _recv_full(conn, klen).decode() if klen else ""
                if op == _OP_SET:
                    tag = _recv_full(conn, 1)[0]
                    (vlen,) = struct.unpack("!I", _recv_full(conn, 4))
                    val = _recv_full(conn, vlen)
                    with self._cv:
                        self._kv[key] = (tag, val)
                        self._cv.notify_all()
                    conn.sendall(b"\x01")
                elif op == _OP_GET:
                    with self._cv:
                        entry = self._kv.get(key)
                    if entry is None:
                        conn.sendall(b"\x01\x00\x00" + struct.pack("!I", 0))
                    else:
                        tag, val = entry
                        conn.sendall(b"\x01\x01" + bytes([tag])
                                     + struct.pack("!I", len(val)) + val)
                elif op == _OP_WAIT:
                    (timeout_s,) = struct.unpack("!d", _recv_full(conn, 8))
                    deadline = time.time() + timeout_s
                    with self._cv:
                        while key not in self._kv:
                            remaining = deadline - time.time()
                            if remaining <= 0:
                                break
                            self._cv.wait(timeout=min(remaining, 1.0))
                        entry = self._kv.get(key)
                    if entry is None:
                        conn.sendall(b"\x00\x00" + struct.pack("!I", 0))
                    else:
                        tag, val = entry
                        conn.sendall(b"\x01" + bytes([tag])
                                     + struct.pack("!I", len(val)) + val)
                elif op == _OP_ADD:
                    (delta,) = struct.unpack("!q", _recv_full(conn, 8))
                    with self._cv:
                        tag, val = self._kv.get(key, (_TAG_I64, b"\0" * 8))
                        cur = struct.unpack("<q", val)[0] if tag == _TAG_I64 \
                            and len(val) == 8 else 0
                        cur += delta
                        self._kv[key] = (_TAG_I64, struct.pack("<q", cur))
                        self._cv.notify_all()
                    conn.sendall(b"\x01" + struct.pack("!q", cur))
                elif op == _OP_DEL:
                    with self._cv:
                        self._kv.pop(key, None)
                        self._cv.notify_all()
                    conn.sendall(b"\x01")
                elif op == _OP_NUM:
                    with self._cv:
                        n = len(self._kv)
                    conn.sendall(b"\x01" + struct.pack("!Q", n))
                else:
                    return
        except (ConnectionError, OSError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass


class _NativeServer:
    """C++ store daemon (csrc/tcp_store.cc) via ctypes."""

    def __init__(self, host, port):
        import ctypes
        from ..core import native
        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        bound = ctypes.c_int(0)
        self._h = lib.pt_store_server_start(host.encode(), port,
                                            ctypes.byref(bound))
        if not self._h:
            raise OSError(f"cannot bind native store at {host}:{port}")
        self.port = bound.value

    def start(self):
        pass  # accept thread already running in C++

    def stop(self):
        if self._h:
            self._lib.pt_store_server_stop(self._h)
            self._h = None

    def num_keys(self):
        return int(self._lib.pt_store_server_num_keys(self._h))


def _decode(tag, val):
    if tag == _TAG_I64 and len(val) == 8:
        return struct.unpack("<q", val)[0]
    if not val:
        return None
    return pickle.loads(val)


class TCPStore:
    """Client (+ optionally server) handle. Values are arbitrary picklable
    objects; counter keys (touched by add()) are i64."""

    def __init__(self, host="127.0.0.1", port=0, is_master=False, world_size=1,
                 timeout=300, use_native=None):
        self.world_size = world_size
        self.timeout = timeout
        self._server = None
        if is_master:
            if use_native is None:
                use_native = os.environ.get("PT_STORE_NATIVE", "1") == "1"
            if use_native:
                try:
                    self._server = _NativeServer(host, port)
                except (RuntimeError, OSError):
                    self._server = None
            if self._server is None:
                self._server = _PyStoreServer(host, port)
            self._server.start()
            port = self._server.port
        self.host, self.port = host, port
        self._lock = threading.Lock()
        self._sock = None
        deadline = time.time() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(f"cannot reach TCPStore at {host}:{port}")
                time.sleep(0.2)

    @property
    def is_native_server(self):
        return isinstance(self._server, _NativeServer)

    # -- wire helpers ------------------------------------------------------
    def _req(self, op, key, payload=b""):
        kb = key.encode()
        return bytes([op]) + struct.pack("!I", len(kb)) + kb + payload

    # -- API ----------------------------------------------------------------
    def set(self, key, value):
        # plain ints store as i64 counters so set()+add() compose (the server's
        # ADD does integer arithmetic on TAG_I64 entries only)
        if type(value) is int and -(2 ** 63) <= value < 2 ** 63:
            tag, data = _TAG_I64, struct.pack("<q", value)
        else:
            tag, data = _TAG_BYTES, pickle.dumps(value)
        msg = self._req(_OP_SET, key,
                        bytes([tag]) + struct.pack("!I", len(data)) + data)
        with self._lock:
            self._sock.sendall(msg)
            ok = _recv_full(self._sock, 1)[0]
        if not ok:
            raise RuntimeError("store set failed")

    def get(self, key):
        with self._lock:
            self._sock.sendall(self._req(_OP_GET, key))
            ok = _recv_full(self._sock, 1)[0]
            has = _recv_full(self._sock, 1)[0]
            tag = _recv_full(self._sock, 1)[0]
            (vlen,) = struct.unpack("!I", _recv_full(self._sock, 4))
            val = _recv_full(self._sock, vlen) if vlen else b""
        if not ok or not has:
            return None
        return _decode(tag, val)

    def wait(self, key, timeout=None):
        t = float(timeout or self.timeout)
        with self._lock:
            self._sock.sendall(self._req(_OP_WAIT, key, struct.pack("!d", t)))
            # server blocks up to t; widen the socket timeout accordingly
            old = self._sock.gettimeout()
            self._sock.settimeout(t + 10)
            try:
                ok = _recv_full(self._sock, 1)[0]
                tag = _recv_full(self._sock, 1)[0]
                (vlen,) = struct.unpack("!I", _recv_full(self._sock, 4))
                val = _recv_full(self._sock, vlen) if vlen else b""
            finally:
                self._sock.settimeout(old)
        if not ok:
            raise TimeoutError(f"wait({key!r}) timed out after {t}s")
        return _decode(tag, val)

    def add(self, key, value=1):
        with self._lock:
            self._sock.sendall(self._req(_OP_ADD, key, struct.pack("!q", value)))
            ok = _recv_full(self._sock, 1)[0]
            (new,) = struct.unpack("!q", _recv_full(self._sock, 8))
        if not ok:
            raise RuntimeError("store add failed")
        return new

    def delete(self, key):
        with self._lock:
            self._sock.sendall(self._req(_OP_DEL, key))
            _recv_full(self._sock, 1)

    def num_keys(self):
        with self._lock:
            self._sock.sendall(self._req(_OP_NUM, ""))
            _recv_full(self._sock, 1)
            (n,) = struct.unpack("!Q", _recv_full(self._sock, 8))
        return n

    def barrier(self, name="default", world_size=None, timeout=None):
        n = world_size or self.world_size
        count = self.add(f"__barrier/{name}/count", 1)
        gen = (count - 1) // n
        target = (gen + 1) * n
        deadline = time.time() + (timeout or self.timeout)
        while self.get(f"__barrier/{name}/count") < target:
            if time.time() > deadline:
                raise TimeoutError(f"barrier {name} timed out")
            time.sleep(0.01)


_global_store: TCPStore | None = None


def create_or_get_global_tcp_store() -> TCPStore:
    global _global_store
    if _global_store is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        master = os.environ.get("PADDLE_MASTER", os.environ.get("MASTER_ENDPOINT",
                                                                "127.0.0.1:0"))
        host, _, port = master.partition(":")
        try:
            _global_store = TCPStore(host or "127.0.0.1", int(port or 0),
                                     is_master=(rank == 0), world_size=world)
        except TimeoutError:
            raise  # client connect timed out — do not mask with a retry
        except OSError:
            # bind failed: the launcher's controller already serves the
            # store at PADDLE_MASTER (it binds the port before spawning
            # us) — every worker, rank 0 included, connects as a client
            _global_store = TCPStore(host or "127.0.0.1", int(port or 0),
                                     is_master=False, world_size=world)
    return _global_store
