"""TCPStore — socket KV rendezvous (reference: phi/core/distributed/store/
tcp_store.h:121, CreateOrGetGlobalTCPStore at store_utils.h:33).

Rank 0 hosts a tiny length-prefixed protocol server; all ranks connect as clients.
Used for multi-process bootstrap metadata, barriers, and host-side object
collectives (the Gloo-analog for small host tensors/objects). Device-side
collectives never touch this — they compile to XLA ICI/DCN ops.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time


def _send_msg(sock, payload: bytes):
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def _recv_msg(sock) -> bytes:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("store connection closed")
        hdr += chunk
    (n,) = struct.unpack("!I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("store connection closed")
        buf += chunk
    return buf


class _StoreServer(threading.Thread):
    def __init__(self, host, port):
        super().__init__(daemon=True)
        self._kv = {}
        self._cv = threading.Condition()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self.port = self._srv.getsockname()[1]
        self._srv.listen(128)

    def run(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                req = pickle.loads(_recv_msg(conn))
                op = req["op"]
                if op == "set":
                    with self._cv:
                        self._kv[req["key"]] = req["value"]
                        self._cv.notify_all()
                    _send_msg(conn, pickle.dumps({"ok": True}))
                elif op == "get":
                    with self._cv:
                        _send_msg(conn, pickle.dumps(
                            {"ok": True, "value": self._kv.get(req["key"])}))
                elif op == "wait":
                    deadline = time.time() + req.get("timeout", 300)
                    with self._cv:
                        while req["key"] not in self._kv:
                            remaining = deadline - time.time()
                            if remaining <= 0:
                                _send_msg(conn, pickle.dumps(
                                    {"ok": False, "error": "timeout"}))
                                break
                            self._cv.wait(timeout=min(remaining, 1.0))
                        else:
                            _send_msg(conn, pickle.dumps(
                                {"ok": True, "value": self._kv[req["key"]]}))
                elif op == "add":
                    with self._cv:
                        cur = self._kv.get(req["key"], 0) + req["value"]
                        self._kv[req["key"]] = cur
                        self._cv.notify_all()
                    _send_msg(conn, pickle.dumps({"ok": True, "value": cur}))
                elif op == "delete":
                    with self._cv:
                        self._kv.pop(req["key"], None)
                        self._cv.notify_all()
                    _send_msg(conn, pickle.dumps({"ok": True}))
        except (ConnectionError, EOFError):
            return


class TCPStore:
    def __init__(self, host="127.0.0.1", port=0, is_master=False, world_size=1,
                 timeout=300):
        self.world_size = world_size
        self.timeout = timeout
        self._server = None
        if is_master:
            self._server = _StoreServer(host, port)
            self._server.start()
            port = self._server.port
        self.host, self.port = host, port
        self._lock = threading.Lock()
        self._sock = None
        deadline = time.time() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(f"cannot reach TCPStore at {host}:{port}")
                time.sleep(0.2)

    def _rpc(self, req):
        with self._lock:
            _send_msg(self._sock, pickle.dumps(req))
            resp = pickle.loads(_recv_msg(self._sock))
        if not resp.get("ok"):
            raise TimeoutError(resp.get("error", "store error"))
        return resp.get("value")

    def set(self, key, value):
        self._rpc({"op": "set", "key": key, "value": value})

    def get(self, key):
        return self._rpc({"op": "get", "key": key})

    def wait(self, key, timeout=None):
        return self._rpc({"op": "wait", "key": key,
                          "timeout": timeout or self.timeout})

    def add(self, key, value=1):
        return self._rpc({"op": "add", "key": key, "value": value})

    def delete(self, key):
        self._rpc({"op": "delete", "key": key})

    def barrier(self, name="default", world_size=None, timeout=None):
        n = world_size or self.world_size
        count = self.add(f"__barrier/{name}/count", 1)
        gen = (count - 1) // n
        target = (gen + 1) * n
        deadline = time.time() + (timeout or self.timeout)
        while self.get(f"__barrier/{name}/count") < target:
            if time.time() > deadline:
                raise TimeoutError(f"barrier {name} timed out")
            time.sleep(0.01)


_global_store: TCPStore | None = None


def create_or_get_global_tcp_store() -> TCPStore:
    global _global_store
    if _global_store is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        master = os.environ.get("PADDLE_MASTER", os.environ.get("MASTER_ENDPOINT",
                                                                "127.0.0.1:0"))
        host, _, port = master.partition(":")
        _global_store = TCPStore(host or "127.0.0.1", int(port or 0),
                                 is_master=(rank == 0), world_size=world)
    return _global_store
