"""ParallelEnv / ParallelMode / gloo_* compatibility surface.

Reference: python/paddle/distributed/parallel.py:757 (ParallelEnv properties
over PADDLE_TRAINER_* env) and fleet/base/topology.py:42 (ParallelMode).
TPU-native: the same env contract is produced by our launcher
(distributed/launch), so ParallelEnv just reads it; the "gloo" CPU barrier
maps to the TCPStore-based host barrier (XLA owns device collectives).
"""
from __future__ import annotations

import os


class ParallelMode:
    """reference: fleet/base/topology.py:42."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class ParallelEnv:
    """reference: distributed/parallel.py ParallelEnv — env-derived process
    coordinates (PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS / ...)."""

    def __init__(self):
        self._rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._device_id = int(os.getenv("FLAGS_selected_tpus",
                                        os.getenv("FLAGS_selected_gpus", "0")))
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []
        self._nrings = int(os.getenv("FLAGS_nccl_nrings", "1"))

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def device_type(self):
        return os.getenv("PADDLE_XCCL_BACKEND", "tpu")

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    @property
    def nrings(self):
        return self._nrings

    # legacy aliases (reference keeps both spellings)
    local_rank = rank
    nranks = world_size
    dev_id = device_id


def is_available():
    """reference: distributed/__init__.py is_available — whether the
    distributed stack can run. Always true here: XLA collectives compile on
    any backend (single-process meshes included)."""
    return True


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-only (host) parallel context (reference: parallel.py
    gloo_init_parallel_env → gloo). Maps to the TCPStore host barrier."""
    from .store import TCPStore
    host, port = server_endpoint.rsplit(":", 1)
    store = TCPStore(host, int(port), is_master=(rank_id == 0),
                     world_size=rank_num)
    global _GLOO_STORE, _GLOO_RANKS
    _GLOO_STORE = store
    _GLOO_RANKS = (rank_id, rank_num)


_GLOO_STORE = None
_GLOO_RANKS = (0, 1)


def gloo_barrier():
    if _GLOO_STORE is None:
        raise RuntimeError("call gloo_init_parallel_env first")
    # shared key: every rank increments the same counter
    _GLOO_STORE.barrier("gloo_barrier", world_size=_GLOO_RANKS[1])


def gloo_release():
    global _GLOO_STORE
    if _GLOO_STORE is not None:
        close = getattr(_GLOO_STORE, "close", None)
        if close:
            close()
        _GLOO_STORE = None


class ReduceType:
    """Partial-placement reduce kinds (reference: pybind auto_parallel
    ReduceType enum used by dist.Partial(reduce_type))."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6
