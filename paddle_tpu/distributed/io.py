"""paddle.distributed.io — persistable save/load helpers.

Reference: python/paddle/distributed/io.py (save_persistables:387,
load_persistables:127, is_persistable:352) — splits a program's persistable
vars into distributed (PS-sharded) and local groups. TPU-native: persistables
are the static.Program's parameter dict; sharded DistTensors save via
distributed.checkpoint, dense ones via framework io.
"""
from __future__ import annotations

import os
import pickle


def is_persistable(var):
    """reference: io.py:352 — parameters and buffers persist; feed/fetch
    temporaries don't."""
    if var is None:
        return False
    persistable = getattr(var, "persistable", None)
    if persistable is not None:
        return bool(persistable)
    return not getattr(var, "stop_gradient", False) or \
        getattr(var, "is_parameter", False)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:387 — write every persistable var of the program."""
    state = {}
    if main_program is not None and hasattr(main_program, "state_dict"):
        state = {k: v for k, v in main_program.state_dict().items()}
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "__persistables__")
    import numpy as np
    blob = {k: np.asarray(getattr(v, "_value", v)) for k, v in state.items()}
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    return path


def load_persistables(executor, dirname, main_program=None, filename=None):
    """reference: io.py:127."""
    path = os.path.join(dirname, filename or "__persistables__")
    with open(path, "rb") as f:
        blob = pickle.load(f)
    if main_program is not None and hasattr(main_program, "set_state_dict"):
        main_program.set_state_dict(blob)
    return blob


def load_inference_model_distributed(dirname, executor):
    """reference: io.py:459 — delegate to the inference artifact loader."""
    from ..static import load_inference_model
    return load_inference_model(dirname, executor)
