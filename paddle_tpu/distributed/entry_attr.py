"""Sparse-table entry policies for the parameter-server path.

Reference: python/paddle/distributed/entry_attr.py — declarative filters for
when an embedding row is admitted/kept in the PS sparse tables
(incubate/distributed/ps.py here).
"""
from __future__ import annotations


class EntryAttr:
    def __init__(self):
        self._name = None

    def _to_attr(self):
        raise NotImplementedError("EntryAttr is base class")


class ProbabilityEntry(EntryAttr):
    """Admit a new row with the given probability (reference:
    entry_attr.py:62)."""

    def __init__(self, probability):
        super().__init__()
        if probability is None or probability < 0 or probability > 1:
            raise ValueError("probability must be a value in [0, 1]")
        self._name = "probability_entry"
        self._probability = probability

    def _to_attr(self):
        return ":".join([self._name, str(self._probability)])


class CountFilterEntry(EntryAttr):
    """Admit a row after it has been seen `count_filter` times (reference:
    entry_attr.py:107)."""

    def __init__(self, count_filter):
        super().__init__()
        if count_filter is None or count_filter < 0:
            raise ValueError(
                "count_filter must be a valid integer greater than 0")
        self._name = "count_filter_entry"
        self._count_filter = count_filter

    def _to_attr(self):
        return ":".join([self._name, str(self._count_filter)])


class ShowClickEntry(EntryAttr):
    """Track show/click statistics per row (reference: entry_attr.py:155)."""

    def __init__(self, show_name, click_name):
        super().__init__()
        if not isinstance(show_name, str) or not isinstance(click_name, str):
            raise ValueError("show_name/click_name must be str")
        self._name = "show_click_entry"
        self._show_name = show_name
        self._click_name = click_name

    def _to_attr(self):
        return ":".join([self._name, self._show_name, self._click_name])
