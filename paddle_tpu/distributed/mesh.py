"""ProcessMesh + placements — the DistTensor metadata model.

Reference: ProcessMesh (paddle/phi/core/distributed/auto_parallel/process_mesh.h,
python/paddle/distributed/auto_parallel/process_mesh.py:85) and Placements
(placement_types.h: Shard/Replicate/Partial).

TPU-native: a ProcessMesh wraps a jax.sharding.Mesh over PJRT devices; placements
translate to NamedSharding PartitionSpecs. Partial is represented EXPLICITLY (see
api.py) since jax's logical arrays cannot carry pending-reduction state: a tensor
that is Partial over axis `a` stores an extra leading dim of size |a|, sharded over
`a`; the logical value is the sum over that dim. Reshard transitions then lower to
XLA collectives (sum -> all_reduce/reduce_scatter; expand -> zero-pad placement).
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Shard(Placement):
    def __init__(self, dim):
        self.dim = int(dim)

    def get_dim(self):
        return self.dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """N-d logical view over devices (reference: process_mesh.py:85)."""

    _unique_counter = [0]

    def __init__(self, mesh, dim_names=None, devices=None):
        arr = np.asarray(mesh)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        self._mesh_ids = arr
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._devices = devices  # optional explicit jax devices
        self._jax_mesh = None

    @property
    def mesh(self):
        return self._mesh_ids.tolist()

    @property
    def shape(self):
        return list(self._mesh_ids.shape)

    @property
    def ndim(self):
        return self._mesh_ids.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._mesh_ids.reshape(-1).tolist()

    def get_dim_size(self, name):
        return self._mesh_ids.shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, pid):
        idx = np.argwhere(self._mesh_ids == pid)
        if idx.size == 0:
            return -1
        return int(idx[0][self._dim_names.index(dim) if isinstance(dim, str) else dim])

    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            if self._devices is not None:
                devs = np.asarray(self._devices, dtype=object).reshape(
                    self._mesh_ids.shape)
            else:
                all_devs = jax.devices()
                flat = [all_devs[i % len(all_devs)]
                        for i in self._mesh_ids.reshape(-1)]
                devs = np.asarray(flat, dtype=object).reshape(self._mesh_ids.shape)
            self._jax_mesh = Mesh(devs, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh_ids, other._mesh_ids)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._mesh_ids.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    def get_group(self, dim_name=None):
        from .env import _group_from_mesh_axis
        return _group_from_mesh_axis(self, dim_name)


def placements_to_spec(placements, ndim: int, dim_names) -> PartitionSpec:
    """placements (one per mesh axis) -> PartitionSpec over tensor dims.

    The analog of the reference's dist_attr dims_mapping (auto_parallel.proto).
    Partial axes contribute nothing to the spec (handled by the explicit leading
    dims in api.py).
    """
    spec = [None] * ndim
    for axis_name, p in zip(dim_names, placements):
        if isinstance(p, Shard):
            d = p.dim % ndim
            if spec[d] is None:
                spec[d] = axis_name
            elif isinstance(spec[d], tuple):
                spec[d] = spec[d] + (axis_name,)
            else:
                spec[d] = (spec[d], axis_name)
    return PartitionSpec(*spec)


def sharding_for(mesh: ProcessMesh, placements, ndim: int) -> NamedSharding:
    return NamedSharding(mesh.jax_mesh(),
                         placements_to_spec(placements, ndim, mesh.dim_names))
