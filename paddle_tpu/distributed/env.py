"""Process/bootstrap environment + communication groups.

Reference: python/paddle/distributed/parallel.py:978 init_parallel_env (TCPStore ->
ProcessGroupNCCL), communication/group.py:29 Group.

TPU-native: multi-host init rides jax.distributed.initialize (the coordination
service is the TCPStore+NCCL-id-exchange analog); ranks are host processes; each
process addresses its local TPU chips. Groups name mesh axes rather than wrap a
comm library — a Group is a view over a ProcessMesh axis whose collectives compile
to XLA ops.
"""
from __future__ import annotations

import os

import numpy as np
import jax

from .mesh import ProcessMesh

_initialized = False
_default_group = None


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_group_rank(global_rank())
    return global_rank()


def global_rank() -> int:
    if _initialized:
        return jax.process_index()
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    if _initialized:
        return jax.process_count()
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def is_initialized() -> bool:
    return _initialized


def init_parallel_env():
    """paddle.distributed.init_parallel_env analog.

    Multi-host: expects PADDLE_MASTER/PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM (set by
    paddle_tpu.distributed.launch) and calls jax.distributed.initialize so all hosts
    join one PJRT runtime. Single host: no-op (all local devices already visible).
    """
    global _initialized, _default_group
    if _initialized:
        return _default_group
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if world > 1:
        # PADDLE_COORDINATOR is set by the launcher (PADDLE_MASTER's port is
        # occupied by its TCPStore); hand-rolled setups may pass the master
        # address directly
        master = os.environ.get("PADDLE_COORDINATOR") \
            or os.environ.get("PADDLE_MASTER")
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        jax.distributed.initialize(coordinator_address=master,
                                   num_processes=world, process_id=rank)
    _initialized = True
    _default_group = _build_default_group()
    return _default_group


def _build_default_group():
    n = len(jax.devices())
    mesh = ProcessMesh(np.arange(n), ["world"])
    return Group(list(range(n)), mesh=mesh, axis="world")


class Group:
    """Communication group = ranks + (mesh, axis) naming for compiled collectives."""

    _next_id = [0]

    def __init__(self, ranks, pg=None, name=None, mesh=None, axis=None):
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        Group._next_id[0] += 1
        self.id = Group._next_id[0]
        self.name = name or f"group_{self.id}"
        self.mesh = mesh
        self.axis = axis

    def get_group_rank(self, global_rank):
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    @property
    def world_size(self):
        return self.nranks

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks}, axis={self.axis})"


def new_group(ranks=None, backend=None, timeout=None):
    """paddle.distributed.new_group — a 1-d mesh over the given device ids."""
    if ranks is None:
        ranks = list(range(len(jax.devices())))
    mesh = ProcessMesh(np.asarray(ranks), ["g"])
    return Group(ranks, mesh=mesh, axis="g")


def get_group(gid=None):
    return _default_group


def _group_from_mesh_axis(mesh: ProcessMesh, dim_name=None):
    if dim_name is None:
        return Group(mesh.process_ids, mesh=mesh, axis=None)
    ax = mesh.dim_names.index(dim_name)
    ids = np.moveaxis(np.asarray(mesh.mesh), ax, 0).reshape(mesh.shape[ax], -1)
    return Group(ids[:, 0].tolist(), mesh=mesh, axis=dim_name)


def barrier(group=None):
    """Host barrier: block until all processes sync (store-based when multi-proc)."""
    if get_world_size() > 1:
        from .store import create_or_get_global_tcp_store
        gen = os.environ.get("PADDLE_RESTART_ID", "0")
        create_or_get_global_tcp_store().barrier(f"dist_barrier/g{gen}",
                                                 world_size=get_world_size())


def get_backend(group=None) -> str:
    return "xla"


def destroy_process_group(group=None):
    global _initialized, _default_group
    _initialized = False
    _default_group = None
