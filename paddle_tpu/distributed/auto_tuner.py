"""Auto-tuner — search over hybrid-parallel configurations.

Reference: distributed/auto_tuner/tuner.py:21 AutoTuner (+ search.py grid,
prune.py validity/memory pruning, cost_model.py) — searches (dp, mp, pp,
sharding, micro-batch, recompute) by launching short profiling jobs.

TPU-native: candidates are mesh factorizations; pruning uses an HBM model
(sharded params/grads/optimizer state + activation working set); ranking uses
an analytic step-time model (MXU compute + ICI collective traffic). A user
`run_fn(cfg) -> seconds` measures the short-listed candidates for the final
pick — on TPU a "profiling job" is one compiled step, no process launch needed.
"""
from __future__ import annotations

import itertools
import math

# v5e-ish defaults; overridable per Tuner
DEFAULT_HW = {
    "flops_per_chip": 197e12,      # bf16 peak
    "hbm_bytes": 16e9,
    "ici_bw": 4.5e10,              # bytes/s per link, one direction
    "mfu_guess": 0.4,
}


class Candidate(dict):
    @property
    def degree(self):
        return self["dp"] * self["mp"] * self["pp"]

    def __repr__(self):
        keys = ("dp", "mp", "pp", "sharding_stage", "micro_batch_size",
                "use_recompute")
        return "Candidate(" + ", ".join(f"{k}={self[k]}" for k in keys) + ")"


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


class AutoTuner:
    def __init__(self, num_devices, model_config, hw=None,
                 tuner_cfg=None):
        """model_config: dict with n_params, flops_per_sample (fwd),
        bytes_per_param (2 bf16 / 4 fp32), activation_bytes_per_sample,
        global_batch_size, n_layers."""
        self.num_devices = num_devices
        self.model = dict(model_config)
        self.hw = {**DEFAULT_HW, **(hw or {})}
        cfg = tuner_cfg or {}
        self.candidate_space = {
            "mp": cfg.get("mp_degree") or _divisors(num_devices),
            "pp": cfg.get("pp_degree") or _divisors(num_devices),
            "sharding_stage": cfg.get("sharding_stage") or [0, 1, 2, 3],
            "micro_batch_size": cfg.get("micro_batch_size") or
                [1, 2, 4, 8, 16],
            "use_recompute": cfg.get("use_recompute")
                if cfg.get("use_recompute") is not None else [False, True],
        }

    # -- enumeration (search.py analog) -------------------------------------
    def enumerate(self):
        out = []
        gbs = self.model["global_batch_size"]
        for mp, pp in itertools.product(self.candidate_space["mp"],
                                        self.candidate_space["pp"]):
            if self.num_devices % (mp * pp):
                continue
            dp = self.num_devices // (mp * pp)
            if gbs % dp:
                continue
            per_dp = gbs // dp
            for mbs, st, rc in itertools.product(
                    self.candidate_space["micro_batch_size"],
                    self.candidate_space["sharding_stage"],
                    self.candidate_space["use_recompute"]):
                if per_dp % mbs:
                    continue
                if pp > 1 and per_dp // mbs < pp:
                    continue  # too few micro-batches to fill the pipeline
                if st > 0 and dp == 1:
                    continue  # nothing to shard over
                out.append(Candidate(
                    dp=dp, mp=mp, pp=pp, sharding_stage=st,
                    micro_batch_size=mbs, use_recompute=rc,
                    acc_steps=per_dp // mbs))
        return out

    # -- memory model (prune.py analog) --------------------------------------
    def memory_bytes(self, c):
        m = self.model
        p_shard = m["n_params"] / (c["mp"] * c["pp"])
        bpp = m.get("bytes_per_param", 2)
        # params + grads (+ fp32 master/moments = 12B/param for adam)
        params = p_shard * bpp
        grads = p_shard * bpp
        opt = p_shard * 12.0
        if c["sharding_stage"] >= 1:
            opt /= c["dp"]
        if c["sharding_stage"] >= 2:
            grads /= c["dp"]
        if c["sharding_stage"] >= 3:
            params /= c["dp"]
        act = m.get("activation_bytes_per_sample", 0) * c["micro_batch_size"] \
            / (c["mp"] * c["pp"])
        if c["use_recompute"]:
            act /= max(math.sqrt(m.get("n_layers", 1)), 1.0)
        if c["pp"] > 1:
            act *= min(c["pp"], c["acc_steps"])  # in-flight micro-batches
        return params + grads + opt + act

    def prune(self, candidates=None):
        cands = candidates if candidates is not None else self.enumerate()
        cap = self.hw["hbm_bytes"] * 0.9
        return [c for c in cands if self.memory_bytes(c) <= cap]

    # -- analytic cost model (cost_model.py analog) ---------------------------
    def step_time(self, c):
        m, hw = self.model, self.hw
        samples = m["global_batch_size"] / c["dp"]  # per DP replica
        flops = 3.0 * m["flops_per_sample"] * samples  # fwd + 2x bwd
        if c["use_recompute"]:
            flops *= 4.0 / 3.0
        # the replica's flops are spread over its mp*pp chips
        compute = flops / (c["mp"] * c["pp"] *
                           hw["flops_per_chip"] * hw["mfu_guess"])
        bpp = m.get("bytes_per_param", 2)
        p_shard = m["n_params"] / (c["mp"] * c["pp"])
        comm = 0.0
        if c["dp"] > 1:  # grad allreduce (ring): 2(n-1)/n
            comm += 2 * (c["dp"] - 1) / c["dp"] * p_shard * bpp / hw["ici_bw"]
        if c["mp"] > 1:  # TP activation collectives ~ 4 allgathers/layer
            act = m.get("activation_bytes_per_sample", 0) * \
                c["micro_batch_size"] / c["mp"]
            comm += 4 * m.get("n_layers", 1) * act * \
                (c["mp"] - 1) / c["mp"] / hw["ici_bw"] * c.get("acc_steps", 1)
        bubble = 0.0
        if c["pp"] > 1:  # 1F1B bubble fraction
            bubble = (c["pp"] - 1) / max(c["acc_steps"], 1) * compute
        return compute + comm + bubble

    # -- search (tuner.py analog) --------------------------------------------
    def tune(self, run_fn=None, top_k=3):
        """Rank pruned candidates by the cost model; if run_fn is given,
        measure the top_k and return the fastest measured config."""
        cands = self.prune()
        if not cands:
            raise RuntimeError("no candidate fits in HBM — reduce model or "
                               "batch, or add devices")
        ranked = sorted(cands, key=self.step_time)
        if run_fn is None:
            return ranked[0], ranked[:top_k]
        best, best_t = None, float("inf")
        for c in ranked[:top_k]:
            t = run_fn(c)
            if t < best_t:
                best, best_t = c, t
        return best, ranked[:top_k]
