"""spawn + launch helpers (reference: python/paddle/distributed/spawn.py and
launch/ module — builds per-process env: PADDLE_TRAINER_ID/ENDPOINTS/MASTER)."""
from __future__ import annotations

import multiprocessing as mp
import os
import socket
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker(fn, rank, nprocs, master, args, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_MASTER"] = master
    fn(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    """paddle.distributed.spawn — launches nprocs host processes.

    On TPU pods there is normally ONE process per host (all local chips addressed
    by that process); nprocs>1 on one host is for CPU-backed multi-process tests.
    """
    master = f"127.0.0.1:{_free_port()}"
    ctx = mp.get_context("spawn")
    procs = []
    env = {k: v for k, v in os.environ.items()}
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, master, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned rank failed with {p.exitcode}")
    return procs


def get_cluster_from_args(args=None):
    return {
        "rank": int(os.environ.get("PADDLE_TRAINER_ID", 0)),
        "world_size": int(os.environ.get("PADDLE_TRAINERS_NUM", 1)),
        "master": os.environ.get("PADDLE_MASTER", ""),
    }
