"""Semi-auto (DTensor) API: shard_tensor / reshard / shard_layer / shard_optimizer.

Reference: python/paddle/distributed/auto_parallel/api.py (shard_tensor:220,
reshard:797, shard_layer:908, shard_optimizer:1735) + the C++ reshard engine
(phi/core/distributed/auto_parallel/reshard/*_reshard_function.cc — the full
{r,s,p} x {r,s,p} transition matrix, nd-mesh and cross-mesh functions).

TPU-native: a DistTensor is a normal Tensor whose `_value` is a jax.Array with a
NamedSharding, plus `_dist_meta = DistMeta(mesh, placements)`. Partial placements
carry an explicit leading reduction dim (see mesh.py docstring), so EVERY transition
in the reference's reshard matrix lowers to one jnp expression + device_put, with
XLA emitting the actual collectives (all_gather for s->r, all_reduce for p->r,
reduce_scatter for p->s, all_to_all for s->s dim moves, send/recv for cross-mesh).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from ..nn.layer_base import Layer, Parameter
from .mesh import (
    ProcessMesh, Placement, Shard, Replicate, Partial, placements_to_spec,
    sharding_for,
)


@dataclass
class DistMeta:
    mesh: ProcessMesh
    placements: tuple  # one per mesh axis; Partial axes have leading dims in _value

    @property
    def partial_axes(self):
        return [i for i, p in enumerate(self.placements) if p.is_partial()]


def _spec_with_partials(meta: DistMeta, logical_ndim: int) -> PartitionSpec:
    """PartitionSpec for the STORED value (leading partial dims + logical dims)."""
    names = meta.mesh.dim_names
    partial_axes = meta.partial_axes
    lead = [names[i] for i in partial_axes]
    body_spec = placements_to_spec(meta.placements, logical_ndim, names)
    return PartitionSpec(*lead, *body_spec)


def _stored_sharding(meta: DistMeta, logical_ndim: int) -> NamedSharding:
    return NamedSharding(meta.mesh.jax_mesh(), _spec_with_partials(meta, logical_ndim))


def is_dist_tensor(t) -> bool:
    return isinstance(t, Tensor) and t._dist_meta is not None


def logical_shape(t: Tensor):
    if not is_dist_tensor(t):
        return tuple(t._value.shape)
    k = len(t._dist_meta.partial_axes)
    return tuple(t._value.shape[k:])


def shard_tensor(x, mesh: ProcessMesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    """paddle.distributed.shard_tensor (api.py:220 analog)."""
    t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    placements = tuple(placements)
    assert len(placements) == mesh.ndim, \
        f"need {mesh.ndim} placements (one per mesh dim), got {len(placements)}"
    val = t._value
    meta = DistMeta(mesh, placements)
    if meta.partial_axes:
        # materialize leading partial dims: slot 0 owns the value, rest zero
        # (reference r_to_p semantics: non-owner ranks hold zeros)
        for ax in reversed(meta.partial_axes):
            n = mesh.shape[ax]
            val = jnp.concatenate(
                [val[None], jnp.zeros((n - 1,) + val.shape, val.dtype)], axis=0)
    sharded = jax.device_put(val, _stored_sharding(meta, t._value.ndim))
    out = Tensor(sharded, stop_gradient=t.stop_gradient if stop_gradient is None
                 else stop_gradient, name=t.name)
    out._dist_meta = meta
    if isinstance(t, Parameter):
        p = Parameter(sharded, trainable=not t.stop_gradient, name=t.name)
        p._dist_meta = meta
        return p
    return out


def dtensor_from_local(local, mesh, placements):
    """Construct from per-rank locals — single-controller: local IS global shard."""
    return shard_tensor(local, mesh, placements)


def dtensor_to_local(t, mesh=None, placements=None):
    return Tensor(t._value, stop_gradient=t.stop_gradient)


def reshard(x: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """paddle.distributed.reshard (api.py:797 analog) — full transition matrix."""
    placements = tuple(placements)
    if not is_dist_tensor(x):
        return shard_tensor(x, mesh, placements)
    src = x._dist_meta
    dst = DistMeta(mesh, placements)
    if src.mesh == dst.mesh and tuple(src.placements) == placements:
        return x

    val = x._value
    src_partials = src.partial_axes
    logical_nd = val.ndim - len(src_partials)

    same_mesh = src.mesh == dst.mesh

    # 1) collapse partial axes that are no longer partial in dst (p->r / p->s):
    #    sum over their leading dims — XLA emits all_reduce/reduce_scatter once we
    #    constrain the output sharding below.
    keep_lead = []  # mesh-axis indices kept partial (ascending = leading dim order)
    sum_dims = []
    for pos, ax in enumerate(src_partials):
        if same_mesh and placements[ax].is_partial():
            keep_lead.append(ax)
        else:
            sum_dims.append(pos)
    if sum_dims:
        # leading dims are ordered by mesh-axis index; sum the dropped ones
        val = jnp.sum(val, axis=tuple(sum_dims))

    # 2) cross-mesh: value now carries only kept partial leading dims
    if not same_mesh:
        # cross-mesh reshard (same_status / global_and_sub_mesh analog):
        # materialize fully (sum remaining partials) then place on the new mesh
        if keep_lead:
            val = jnp.sum(val, axis=tuple(range(len(keep_lead))))
            keep_lead = []
        new_meta = DistMeta(dst.mesh, placements)
        if new_meta.partial_axes:
            for ax in reversed(new_meta.partial_axes):
                n = dst.mesh.shape[ax]
                val = jnp.concatenate(
                    [val[None], jnp.zeros((n - 1,) + val.shape, val.dtype)], axis=0)
        out_val = jax.device_put(val, _stored_sharding(new_meta, logical_nd))
        out = Tensor(out_val, stop_gradient=x.stop_gradient, name=x.name)
        out._dist_meta = new_meta
        return out

    # 3) same mesh: add new partial leading dims (r->p, s->p) at their sorted slot
    new_partials = DistMeta(dst.mesh, placements).partial_axes
    import bisect
    for ax in [a for a in new_partials if a not in keep_lead]:
        n = mesh.shape[ax]
        pos = bisect.bisect_left(keep_lead, ax)
        expanded = jnp.concatenate(
            [val[None], jnp.zeros((n - 1,) + val.shape, val.dtype)], axis=0)
        val = jnp.moveaxis(expanded, 0, pos)
        keep_lead.insert(pos, ax)

    new_meta = DistMeta(dst.mesh, placements)
    out_val = jax.device_put(val, _stored_sharding(new_meta, logical_nd))
    out = Tensor(out_val, stop_gradient=x.stop_gradient, name=x.name)
    out._dist_meta = new_meta
    return out


def full_value(x: Tensor):
    """Materialize the logical (replicated) value of any DistTensor."""
    if not is_dist_tensor(x):
        return x._value
    k = len(x._dist_meta.partial_axes)
    v = x._value
    if k:
        v = jnp.sum(v, axis=tuple(range(k)))
    return v


def shard_layer(layer: Layer, process_mesh: ProcessMesh, shard_fn: Callable = None,
                input_fn=None, output_fn=None) -> Layer:
    """paddle.distributed.shard_layer (api.py:908 analog).

    shard_fn(sublayer_name, sublayer, process_mesh) annotates parameters in place
    (typically via shard_tensor on .weight/.bias). Default: replicate everything.
    """
    def default_shard(name, sub, mesh):
        for pname, p in list(sub._parameters.items()):
            if p is None or p._dist_meta is not None:
                continue
            sub._parameters[pname] = shard_tensor(
                p, mesh, [Replicate() for _ in range(mesh.ndim)])

    fn = shard_fn or default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """paddle.distributed.shard_optimizer (api.py:1735 analog).

    Wraps slot creation so optimizer states inherit (or override via shard_fn) the
    parameter shardings — ZeRO-style state partitioning is `shard_fn=ShardingStage1`.
    """
    orig_ensure = optimizer._ensure_slots

    def ensure(params):
        orig_ensure(params)
        for p in params:
            if p._dist_meta is None:
                continue
            slots = optimizer._slots[id(p)]
            for k, v in slots.items():
                if not isinstance(v, jax.Array) or v.ndim != len(logical_shape(p)):
                    continue
                if shard_fn is not None:
                    slots[k] = shard_fn(k, p, v)
                else:
                    slots[k] = jax.device_put(
                        v, sharding_for(p._dist_meta.mesh, p._dist_meta.placements,
                                        v.ndim))

    optimizer._ensure_slots = ensure
    return optimizer


class ShardingStage1:
    """ZeRO-1: shard optimizer states over the data axis (reference:
    auto_parallel/api.py:1430 ShardingStage1 + dygraph_sharding_optimizer.py:54)."""

    def __init__(self, axis_name="dp", mesh=None):
        self.axis = axis_name
        self.mesh = mesh

    def __call__(self, slot_name, param, slot_value):
        mesh = self.mesh or (param._dist_meta.mesh if param._dist_meta else None)
        if mesh is None or self.axis not in mesh.dim_names:
            return slot_value
        # shard the largest dim of the state over the data axis when divisible
        ax_size = mesh.get_dim_size(self.axis)
        # prefer the first-largest dim (stable) so the choice is deterministic
        for d in np.argsort([-s for s in slot_value.shape], kind="stable"):
            if slot_value.shape[int(d)] % ax_size == 0 and slot_value.shape[int(d)] > 1:
                spec = [None] * slot_value.ndim
                spec[int(d)] = self.axis
                # keep existing param sharding on other dims
                if param._dist_meta is not None:
                    base = placements_to_spec(param._dist_meta.placements,
                                              slot_value.ndim, mesh.dim_names)
                    for i, s in enumerate(base):
                        if s is not None and i != int(d):
                            spec[i] = s
                        if s is not None and i == int(d):
                            spec[i] = (self.axis, s) if s != self.axis else s
                return jax.device_put(
                    slot_value, NamedSharding(mesh.jax_mesh(), PartitionSpec(*spec)))
        return slot_value


ShardingStage2 = ShardingStage1  # grads shard implicitly under GSPMD; states same
ShardingStage3 = ShardingStage1  # param sharding handled via shard_tensor(Shard(0))


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel embedding/linear in one call (reference:
    distributed/collective.py split — builds the mp layer and applies it).
    Creates the fleet mp layer on first use; hold the returned layer via
    split.last_layer to train its parameters.
    """
    from .fleet import mp_layers as mp

    if operation == "embedding":
        if axis != 0:
            raise ValueError("the axis for embedding split must be 0")
        layer = mp.VocabParallelEmbedding(size[0], size[1],
                                          weight_attr=weight_attr)
    elif operation == "linear":
        if axis == 0:
            layer = mp.RowParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         input_is_parallel=not gather_out)
        elif axis == 1:
            layer = mp.ColumnParallelLinear(size[0], size[1],
                                            weight_attr=weight_attr,
                                            has_bias=bias_attr is not False,
                                            gather_output=gather_out)
        else:
            raise ValueError("axis must be 0 (row) or 1 (column) for linear")
    else:
        raise ValueError(
            f"operation must be 'linear' or 'embedding', got {operation}")
    split.last_layer = layer
    return layer(x)


split.last_layer = None
