"""Collective watchdog — cluster-wide hang detection.

Reference: CommTaskManager (phi/core/distributed/comm_task_manager.h:37) —
background threads track in-flight collective progress, time out hung ops
(comm_task_manager.cc:273), publish per-rank traces into the Store so the
slowest/hung rank is identifiable cluster-wide, with ErrorHandlingMode
{NoHandling, TearDown}.

TPU-native: XLA collectives are compiled into the step, so per-op tracking
becomes per-STEP tracking — each rank ticks a step counter into the TCPStore;
the watchdog thread compares all ranks' progress and ages, flags ranks whose
heartbeat stalls past `timeout`, and (TearDown mode) aborts the process so the
launcher/elastic layer can relaunch.
"""
from __future__ import annotations

import os
import threading
import time


class ErrorHandlingMode:
    NoHandling = "no_handling"
    TearDown = "tear_down"


class Watchdog:
    def __init__(self, store, rank, world_size, timeout=300.0,
                 mode=ErrorHandlingMode.NoHandling, on_hang=None,
                 poll_interval=None, prefix="__watchdog"):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.timeout = timeout
        self.mode = mode
        self.on_hang = on_hang
        self.prefix = prefix
        self._poll = poll_interval or max(min(timeout / 4, 10.0), 0.05)
        self._step = 0
        self._stop = threading.Event()
        self._thread = None
        self.hung_ranks: list[int] = []

    # -- producer side ------------------------------------------------------
    def tick(self, step=None):
        """Call once per train step (cheap: one store write)."""
        self._step = self._step + 1 if step is None else step
        self.store.set(f"{self.prefix}/{self.rank}",
                       {"step": self._step, "ts": time.time()})

    # -- monitor side -------------------------------------------------------
    def _scan(self):
        now = time.time()
        hung = []
        progress = {}
        for r in range(self.world_size):
            ent = self.store.get(f"{self.prefix}/{r}")
            if ent is None:
                continue  # not started yet
            progress[r] = ent["step"]
            if now - ent["ts"] > self.timeout:
                hung.append(r)
        return hung, progress

    def _run(self):
        reported: set[int] = set()
        while not self._stop.wait(self._poll):
            hung, progress = self._scan()
            self.hung_ranks = hung  # cleared automatically on recovery
            new = [r for r in hung if r not in reported]
            reported = set(hung)
            if new:  # edge-triggered: fire once per incident, not per poll
                trace = {"hung": hung, "progress": progress,
                         "reporter": self.rank, "ts": time.time()}
                self.store.set(f"{self.prefix}/report", trace)
                if self.on_hang is not None:
                    self.on_hang(trace)
                if self.mode == ErrorHandlingMode.TearDown:
                    os._exit(124)  # launcher sees the failure and relaunches

    def start(self):
        self.tick(0)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def last_report(self):
        return self.store.get(f"{self.prefix}/report")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
