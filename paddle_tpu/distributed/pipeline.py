"""SPMD collective pipeline — the compute core of pipeline parallelism.

Reference analog: fleet/meta_parallel/pipeline_parallel.py (1F1B at
forward_backward_pipeline:684) + p2p_communication.py over NCCL send/recv.

TPU-native design: the pipeline is ONE compiled program. Stages are structurally
identical (transformer repeat blocks); per-stage params carry a leading [S] dim
sharded over the 'pp' mesh axis. A lax.scan steps microbatches through the ring:
each tick every stage runs its block, then activations ppermute to the next stage
over ICI. Backward is jax autodiff of the scan — XLA schedules it as the reverse
pipeline (the 1F1B-equivalent interleave emerges from the dependence structure
rather than a hand-written schedule); `remat` trades activation memory like the
reference's recompute_interval.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from ..core.jax_compat import shard_map  # version-adapted (core/jax_compat.py)


def _psum(y, axis):
    """psum that survives the XLA *CPU* backend's AllReducePromotion pass.

    jax 0.7 lowers an in-shard_map psum with a sharding annotation INSIDE the
    reduction body (sdy.sharding_constraint -> an HLO `copy`); promoting a
    16-bit all-reduce then dies in CloneAllReduce ("Invalid binary
    instruction opcode copy"). CPU promotes all 16-bit all-reduces, so
    reduce in f32 there; real TPU backends reduce bf16 natively and keep the
    half-width ICI traffic."""
    if jax.default_backend() == "cpu" and y.dtype in (jnp.bfloat16,
                                                      jnp.float16):
        return jax.lax.psum(y.astype(jnp.float32), axis).astype(y.dtype)
    return jax.lax.psum(y, axis)


def spmd_pipeline(stage_fn, stacked_params, x_mb, mesh, axis="pp", remat=False):
    """Run microbatches through a ring of identical stages.

    stage_fn(params, x) -> y, with y.shape == x.shape (inter-stage activation).
    stacked_params: pytree, each leaf [S, ...] (S = #stages), sharded over `axis`.
    x_mb: [M, microbatch, ...] inputs for stage 0, replicated over `axis`; any
          dp/mp sharding on the microbatch dims stays automatic under GSPMD.
    Returns y_mb [M, microbatch, ...] — last stage's outputs, replicated over axis.
    """
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    S = jmesh.shape[axis]
    M = x_mb.shape[0]
    assert M >= 1
    T = M + S - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # dp (and any other non-pp axis) is automatic: the input batch keeps its own
    # sharding and GSPMD partitions the body; specs only name the manual pp axis.
    batch_spec = P()

    def per_device(params_l, x):
        params = jax.tree_util.tree_map(lambda a: a[0], params_l)
        idx = jax.lax.axis_index(axis)

        def step(state, t):
            mb = jax.lax.dynamic_index_in_dim(x, jnp.clip(t, 0, M - 1), 0,
                                              keepdims=False)
            cur = jnp.where(idx == 0, mb, state)
            out = fn(params, cur)
            perm = [(i, (i + 1) % S) for i in range(S)]
            nxt = jax.lax.ppermute(out, axis, perm)
            return nxt, out

        _, outs = jax.lax.scan(step, jnp.zeros_like(x[0]), jnp.arange(T))
        y = outs[S - 1:]                       # [M, mb, ...] valid on last stage
        y = jnp.where(idx == S - 1, y, jnp.zeros_like(y))
        return _psum(y, axis)           # replicate last stage's outputs

    spec_params = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    # manual over the pipeline axis only: dp/mp/sharding axes stay automatic, so
    # GSPMD partitions the stage body (TP matmuls, dp batch) inside the ring.
    return shard_map(per_device, mesh=jmesh,
                     in_specs=(spec_params, batch_spec),
                     out_specs=batch_spec, axis_names={axis},
                     check_vma=False)(stacked_params, x_mb)


def scheduled_pipeline(stage_fn, stacked_params, x_mb, mesh, axis="pp",
                       zero_bubble=False):
    """Explicit micro-batch schedule: 1F1B / ZBH1 (reference:
    fleet/meta_parallel/pipeline_parallel.py:684 forward_backward_pipeline,
    passes/pipeline_scheduler_pass/pipeline_zero_bubble.py).

    Unlike :func:`spmd_pipeline` (whole-scan autodiff — the FThenB residency
    policy: XLA keeps every microbatch's intermediates), this runtime owns the
    backward schedule via ``jax.custom_vjp``:

    - **forward**: ring scan; each stage stores ONLY its M stage-boundary
      inputs, sharded over `axis` (per-device boundary memory = M x microbatch,
      the 1F1B residency bound with recompute — nothing else survives).
    - **backward (1F1B)**: reverse ring scan; at each tick a stage recomputes
      one microbatch's block from its saved boundary and applies its vjp —
      at most one microbatch's intermediates are ever live per device; dx
      ppermutes upstream; dw accumulates into the stage's param-grad shard.
    - **backward (ZBH1, zero_bubble=True)**: the reference's W-split, the
      TPU-native way: the reverse scan computes ONLY dx (XLA dead-code
      eliminates the dw GEMMs), so the serial cross-stage dependency chain —
      the thing that makes the bubble — contains just the dx work; dw for all
      stages/microbatches is computed afterwards in a scan with NO ppermute,
      i.e. completely off the ring's critical path, free for XLA's
      latency-hiding scheduler to overlap. Costs one extra forward recompute
      and an M-deep dy buffer per stage — the same memory-for-bubble trade
      zero-bubble makes.

    Micro-timing within a tick is XLA's prerogative (there is no host schedule
    loop to drive on TPU); what each mode pins is the *residency policy* and
    the *dependency structure*, which is what the schedules differ by.
    Compiled-program evidence that the W-split lands as claimed — loop
    computations carrying the dw matmuls with ZERO collective-permutes,
    disjoint from the permute-carrying ring loops — is captured in
    ``docs/artifacts/zbh1_schedule_proof.json`` (regenerated by
    tests/test_pipeline_schedules.py::TestZBH1ScheduleArtifact).

    RNG: one base key is drawn per call and folded with (stage, microbatch),
    so the backward recompute sees the forward's randomness by construction.
    """
    from ..core import random as _random

    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    S = jmesh.shape[axis]
    M = x_mb.shape[0]
    T = M + S - 1
    batch_spec = P()
    key_base = _random.next_key()

    def run_stage(params, x, stage_i, mb_i):
        k = jax.random.fold_in(jax.random.fold_in(key_base, stage_i), mb_i)
        with _random.provide_key(k):
            return stage_fn(params, x)

    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    def _masked_row_write(buf, row_i, value, valid):
        """Write `value` into buf[row_i] only when valid (read-modify-write —
        keeps the scan carry at exactly M rows instead of stacking T ticks)."""
        old = jax.lax.dynamic_index_in_dim(buf, row_i, 0, keepdims=False)
        new = jnp.where(valid, value, old)
        return jax.lax.dynamic_update_slice_in_dim(buf, new[None], row_i, 0)

    def fwd_device(params_l, x):
        params = jax.tree_util.tree_map(lambda a: a[0], params_l)
        idx = jax.lax.axis_index(axis)

        def step(carry, t):
            state, y_buf, resid_buf = carry
            mb = jax.lax.dynamic_index_in_dim(x, jnp.clip(t, 0, M - 1), 0,
                                              keepdims=False)
            cur = jnp.where(idx == 0, mb, state)
            f = t - idx                       # this stage's microbatch number
            fc = jnp.clip(f, 0, M - 1)
            valid = (f >= 0) & (f < M)
            resid_buf = _masked_row_write(resid_buf, fc, cur, valid)
            out = run_stage(params, cur, idx, fc)
            yf = t - (S - 1)                  # last stage's microbatch number
            y_buf = _masked_row_write(y_buf, jnp.clip(yf, 0, M - 1), out,
                                      (yf >= 0) & (yf < M))
            return (jax.lax.ppermute(out, axis, fwd_perm), y_buf,
                    resid_buf), None

        zero_mb = jnp.zeros_like(x[0])
        (_, y_buf, resid), _ = jax.lax.scan(
            step, (zero_mb, jnp.zeros_like(x), jnp.zeros_like(x)),
            jnp.arange(T))
        y = jnp.where(idx == S - 1, y_buf, jnp.zeros_like(y_buf))
        return _psum(y, axis), resid[None]  # [1(pp), M, mb...]

    def bwd_device(params_l, resid_l, dy_mb):
        params = jax.tree_util.tree_map(lambda a: a[0], params_l)
        resid = resid_l[0]                        # [M, mb...]
        idx = jax.lax.axis_index(axis)
        U = M + S - 1

        def tick(carry, u):
            state, dw_acc, dx_buf, dy_buf = carry
            b = u - (S - 1 - idx)                 # this stage's microbatch
            bc = jnp.clip(b, 0, M - 1)
            valid = (b >= 0) & (b < M)
            dy_last = jax.lax.dynamic_index_in_dim(dy_mb, bc, 0,
                                                   keepdims=False)
            dy = jnp.where(idx == S - 1, dy_last, state)
            x_b = jax.lax.dynamic_index_in_dim(resid, bc, 0, keepdims=False)
            if zero_bubble:
                # dx-only chain: dw GEMMs are dead code here (W-split); dy is
                # buffered (microbatch-aligned) for the deferred W pass
                _, vjp_x = jax.vjp(
                    lambda xx: run_stage(params, xx, idx, bc), x_b)
                (dx,) = vjp_x(dy)
                dy_buf = _masked_row_write(dy_buf, bc, dy, valid)
            else:
                _, vjp_fn = jax.vjp(
                    lambda pp, xx: run_stage(pp, xx, idx, bc), params, x_b)
                dw, dx = vjp_fn(dy)
                dw_acc = jax.tree_util.tree_map(
                    lambda acc, g: acc + jnp.where(valid, g, 0), dw_acc, dw)
            dx = jnp.where(valid, dx, jnp.zeros_like(dx))
            dx_buf = _masked_row_write(dx_buf, bc, dx, valid)
            nxt = jax.lax.ppermute(dx, axis, bwd_perm)
            return (nxt, dw_acc, dx_buf, dy_buf), None

        dw0 = jax.tree_util.tree_map(lambda a: jnp.zeros_like(a), params)
        zero_buf = jnp.zeros((M,) + dy_mb.shape[1:], dy_mb.dtype)
        (_, dw_acc, dx_buf, dy_buf), _ = jax.lax.scan(
            tick, (jnp.zeros_like(dy_mb[0]), dw0, zero_buf,
                   zero_buf if zero_bubble else jnp.zeros((), dy_mb.dtype)),
            jnp.arange(U))

        if zero_bubble:
            # deferred W pass: per-stage, no ppermute — off the ring's
            # critical path (dy_buf is already microbatch-aligned)

            def w_tick(dw_acc, bm):
                x_b = jax.lax.dynamic_index_in_dim(resid, bm, 0,
                                                   keepdims=False)
                dy_b = jax.lax.dynamic_index_in_dim(dy_buf, bm, 0,
                                                    keepdims=False)
                _, vjp_p = jax.vjp(
                    lambda pp: run_stage(pp, x_b, idx, bm), params)
                (dw,) = vjp_p(dy_b)
                return jax.tree_util.tree_map(lambda a, g: a + g,
                                              dw_acc, dw), None

            dw_acc, _ = jax.lax.scan(w_tick, dw0, jnp.arange(M))

        dx_mb = jnp.where(idx == 0, dx_buf, jnp.zeros_like(dx_buf))
        dparams = jax.tree_util.tree_map(lambda a: a[None], dw_acc)
        return dparams, _psum(dx_mb, axis)

    spec_params = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    resid_spec = P(axis)

    fwd_sm = shard_map(fwd_device, mesh=jmesh,
                       in_specs=(spec_params, batch_spec),
                       out_specs=(batch_spec, resid_spec), axis_names={axis},
                       check_vma=False)
    bwd_sm = shard_map(bwd_device, mesh=jmesh,
                       in_specs=(spec_params, resid_spec, batch_spec),
                       out_specs=(spec_params, batch_spec), axis_names={axis},
                       check_vma=False)

    @jax.custom_vjp
    def pipe(params, x):
        y, _ = fwd_sm(params, x)
        return y

    def pipe_fwd(params, x):
        y, resid = fwd_sm(params, x)
        return y, (params, resid)

    def pipe_bwd(res, dy):
        params, resid = res
        dparams, dx = bwd_sm(params, resid, dy)
        return dparams, dx

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(stacked_params, x_mb)


def interleaved_pipeline(stage_fn, stacked_params, x_mb, mesh, axis="pp",
                         num_chunks=2, remat=False):
    """Interleaved (VPP) schedule: each device owns `num_chunks` non-adjacent model
    chunks (reference: PipelineParallelWithInterleave, pipeline_parallel.py:1308).
    Param leaves are [S*num_chunks, ...] in ring order; the ring is traversed
    num_chunks times per microbatch."""
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    S = jmesh.shape[axis]
    V = num_chunks
    M = x_mb.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    batch_spec = P()

    def per_device(params_l, x):
        # leaf [V, ...]: chunk v on this device is global stage (v*S + idx)
        idx = jax.lax.axis_index(axis)

        def run_ring(carry_x, v):
            # leaf local shape [V, 1(pp-local), L, ...]: pick chunk v, drop pp dim
            chunk_params = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False)[0],
                params_l)
            T = M + S - 1

            def step(state, t):
                mb = jax.lax.dynamic_index_in_dim(carry_x, jnp.clip(t, 0, M - 1), 0,
                                                  keepdims=False)
                cur = jnp.where(idx == 0, mb, state)
                out = fn(chunk_params, cur)
                perm = [(i, (i + 1) % S) for i in range(S)]
                return jax.lax.ppermute(out, axis, perm), out

            _, outs = jax.lax.scan(step, jnp.zeros_like(carry_x[0]), jnp.arange(T))
            y = outs[S - 1:]
            y = jnp.where(idx == S - 1, y, jnp.zeros_like(y))
            return _psum(y, axis), None

        y, _ = jax.lax.scan(run_ring, x, jnp.arange(V))
        return y

    spec_params = jax.tree_util.tree_map(lambda _: P(None, axis), stacked_params)

    # reshape leaves [S*V, ...] -> [V, S, ...] so chunk-major scan + pp shard works
    def reshape_leaf(a):
        return a.reshape((V, S) + a.shape[1:])

    stacked_vs = jax.tree_util.tree_map(reshape_leaf, stacked_params)
    return shard_map(per_device, mesh=jmesh,
                     in_specs=(spec_params, batch_spec),
                     out_specs=batch_spec, axis_names={axis},
                     check_vma=False)(stacked_vs, x_mb)


def scheduled_interleaved_pipeline(stage_fn, stacked_params, x_mb, mesh,
                                   axis="pp", num_chunks=2):
    """ZBVPP: zero-bubble x interleaved virtual chunks (reference:
    passes/pipeline_scheduler_pass/pipeline_zero_bubble.py composed with
    PipelineParallelWithInterleave).

    Composition of :func:`scheduled_pipeline`'s W-split with
    :func:`interleaved_pipeline`'s chunk loop:

    - **forward**: the ring is traversed ``num_chunks`` times (chunk v on
      device d = global stage v*S+d); each chunk pass stores only its M
      stage-boundary inputs — residency [V, M, microbatch] per device.
    - **backward**: chunks unwind in reverse; each reverse ring computes
      ONLY dx (the W-split — the serial cross-chunk/cross-stage chain holds
      just dx work) and buffers dy per (chunk, microbatch).
    - **deferred W pass**: all V*M dw contributions run afterwards with NO
      ppermute — off the ring's critical path, XLA-overlappable, exactly the
      zero-bubble trade paid with an extra forward recompute and the
      [V, M]-deep dy buffer.

    Params: leaves [S*num_chunks, ...] in ring order (chunk-major after the
    internal [V, S] reshape), sharded over `axis`. Differentiable like
    scheduled_pipeline (custom_vjp).
    """
    from ..core import random as _random

    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    S = jmesh.shape[axis]
    V = num_chunks
    M = x_mb.shape[0]
    T = M + S - 1
    batch_spec = P()
    key_base = _random.next_key()

    def run_stage(params, x, stage_i, mb_i):
        k = jax.random.fold_in(jax.random.fold_in(key_base, stage_i), mb_i)
        with _random.provide_key(k):
            return stage_fn(params, x)

    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    def _masked_row_write(buf, row_i, value, valid):
        old = jax.lax.dynamic_index_in_dim(buf, row_i, 0, keepdims=False)
        new = jnp.where(valid, value, old)
        return jax.lax.dynamic_update_slice_in_dim(buf, new[None], row_i, 0)

    def _chunk(params_l, v):
        # local leaf [V, 1(pp), ...] -> chunk v's stage params [...]
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, v, 0,
                                                   keepdims=False)[0],
            params_l)

    def fwd_device(params_l, x):
        idx = jax.lax.axis_index(axis)

        def chunk_fwd(carry_x, v):
            params = _chunk(params_l, v)
            sid = v * S + idx

            def step(carry, t):
                state, y_buf, resid_buf = carry
                mb = jax.lax.dynamic_index_in_dim(
                    carry_x, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                cur = jnp.where(idx == 0, mb, state)
                f = t - idx
                fc = jnp.clip(f, 0, M - 1)
                valid = (f >= 0) & (f < M)
                resid_buf = _masked_row_write(resid_buf, fc, cur, valid)
                out = run_stage(params, cur, sid, fc)
                yf = t - (S - 1)
                y_buf = _masked_row_write(y_buf, jnp.clip(yf, 0, M - 1), out,
                                          (yf >= 0) & (yf < M))
                return (jax.lax.ppermute(out, axis, fwd_perm), y_buf,
                        resid_buf), None

            (_, y_buf, resid_buf), _ = jax.lax.scan(
                step, (jnp.zeros_like(carry_x[0]), jnp.zeros_like(carry_x),
                       jnp.zeros_like(carry_x)), jnp.arange(T))
            y = jnp.where(idx == S - 1, y_buf, jnp.zeros_like(y_buf))
            return _psum(y, axis), resid_buf

        y, resid_v = jax.lax.scan(chunk_fwd, x, jnp.arange(V))
        return y, resid_v[None]                  # [1(pp), V, M, mb...]

    def bwd_device(params_l, resid_l, dy_mb):
        resid = resid_l[0]                       # [V, M, mb...]
        idx = jax.lax.axis_index(axis)
        U = M + S - 1

        def chunk_bwd(carry_dy, v):
            params = _chunk(params_l, v)
            sid = v * S + idx
            resid_c = jax.lax.dynamic_index_in_dim(resid, v, 0,
                                                   keepdims=False)

            def tick(carry, u):
                state, dx_buf, dy_buf = carry
                b = u - (S - 1 - idx)
                bc = jnp.clip(b, 0, M - 1)
                valid = (b >= 0) & (b < M)
                dy_last = jax.lax.dynamic_index_in_dim(carry_dy, bc, 0,
                                                       keepdims=False)
                dy = jnp.where(idx == S - 1, dy_last, state)
                x_b = jax.lax.dynamic_index_in_dim(resid_c, bc, 0,
                                                   keepdims=False)
                # dx-only chain (W-split): dw GEMMs are dead code here
                _, vjp_x = jax.vjp(
                    lambda xx: run_stage(params, xx, sid, bc), x_b)
                (dx,) = vjp_x(dy)
                dy_buf = _masked_row_write(dy_buf, bc, dy, valid)
                dx = jnp.where(valid, dx, jnp.zeros_like(dx))
                dx_buf = _masked_row_write(dx_buf, bc, dx, valid)
                return (jax.lax.ppermute(dx, axis, bwd_perm), dx_buf,
                        dy_buf), None

            zero_buf = jnp.zeros((M,) + dy_mb.shape[1:], dy_mb.dtype)
            (_, dx_buf, dy_buf), _ = jax.lax.scan(
                tick, (jnp.zeros_like(dy_mb[0]), zero_buf, zero_buf),
                jnp.arange(U))
            dx_mb = jnp.where(idx == 0, dx_buf, jnp.zeros_like(dx_buf))
            # stage-0 dx of chunk v is the upstream dy of chunk v-1
            return _psum(dx_mb, axis), dy_buf

        dx_final, dy_bufs_rev = jax.lax.scan(chunk_bwd, dy_mb,
                                             jnp.arange(V - 1, -1, -1))
        dy_bufs = jnp.flip(dy_bufs_rev, 0)       # chunk-major [V, M, mb...]

        # deferred W pass: V*M dw contributions, NO ppermute anywhere —
        # completely off the ring's serial chain
        def w_chunk(_, v):
            params = _chunk(params_l, v)
            sid = v * S + idx
            resid_c = jax.lax.dynamic_index_in_dim(resid, v, 0,
                                                   keepdims=False)
            dy_c = jax.lax.dynamic_index_in_dim(dy_bufs, v, 0,
                                                keepdims=False)

            def w_tick(dw_acc, bm):
                x_b = jax.lax.dynamic_index_in_dim(resid_c, bm, 0,
                                                   keepdims=False)
                dy_b = jax.lax.dynamic_index_in_dim(dy_c, bm, 0,
                                                    keepdims=False)
                _, vjp_p = jax.vjp(
                    lambda pp: run_stage(pp, x_b, sid, bm), params)
                (dw,) = vjp_p(dy_b)
                return jax.tree_util.tree_map(lambda a, g: a + g,
                                              dw_acc, dw), None

            dw0 = jax.tree_util.tree_map(jnp.zeros_like, params)
            dw_v, _ = jax.lax.scan(w_tick, dw0, jnp.arange(M))
            return None, dw_v

        _, dw_stacked = jax.lax.scan(w_chunk, None, jnp.arange(V))
        dparams = jax.tree_util.tree_map(lambda a: a[:, None], dw_stacked)
        return dparams, dx_final

    spec_params = jax.tree_util.tree_map(lambda _: P(None, axis),
                                         stacked_params)
    resid_spec = P(axis)

    fwd_sm = shard_map(fwd_device, mesh=jmesh,
                       in_specs=(spec_params, batch_spec),
                       out_specs=(batch_spec, resid_spec), axis_names={axis},
                       check_vma=False)
    bwd_sm = shard_map(bwd_device, mesh=jmesh,
                       in_specs=(spec_params, resid_spec, batch_spec),
                       out_specs=(spec_params, batch_spec), axis_names={axis},
                       check_vma=False)

    @jax.custom_vjp
    def pipe(params_vs, x):
        y, _ = fwd_sm(params_vs, x)
        return y

    def pipe_fwd(params_vs, x):
        y, resid = fwd_sm(params_vs, x)
        return y, (params_vs, resid)

    def pipe_bwd(res, dy):
        params_vs, resid = res
        dparams, dx = bwd_sm(params_vs, resid, dy)
        return dparams, dx

    pipe.defvjp(pipe_fwd, pipe_bwd)

    # [S*V, ...] ring order -> chunk-major [V, S, ...] (differentiable
    # reshape: grads flow back to the caller's stacked form)
    stacked_vs = jax.tree_util.tree_map(
        lambda a: a.reshape((V, S) + a.shape[1:]), stacked_params)
    return pipe(stacked_vs, x_mb)
