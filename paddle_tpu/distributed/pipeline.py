"""SPMD collective pipeline — the compute core of pipeline parallelism.

Reference analog: fleet/meta_parallel/pipeline_parallel.py (1F1B at
forward_backward_pipeline:684) + p2p_communication.py over NCCL send/recv.

TPU-native design: the pipeline is ONE compiled program. Stages are structurally
identical (transformer repeat blocks); per-stage params carry a leading [S] dim
sharded over the 'pp' mesh axis. A lax.scan steps microbatches through the ring:
each tick every stage runs its block, then activations ppermute to the next stage
over ICI. Backward is jax autodiff of the scan — XLA schedules it as the reverse
pipeline (the 1F1B-equivalent interleave emerges from the dependence structure
rather than a hand-written schedule); `remat` trades activation memory like the
reference's recompute_interval.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map


def spmd_pipeline(stage_fn, stacked_params, x_mb, mesh, axis="pp", remat=False):
    """Run microbatches through a ring of identical stages.

    stage_fn(params, x) -> y, with y.shape == x.shape (inter-stage activation).
    stacked_params: pytree, each leaf [S, ...] (S = #stages), sharded over `axis`.
    x_mb: [M, microbatch, ...] inputs for stage 0, replicated over `axis`; any
          dp/mp sharding on the microbatch dims stays automatic under GSPMD.
    Returns y_mb [M, microbatch, ...] — last stage's outputs, replicated over axis.
    """
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    S = jmesh.shape[axis]
    M = x_mb.shape[0]
    assert M >= 1
    T = M + S - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    # dp (and any other non-pp axis) is automatic: the input batch keeps its own
    # sharding and GSPMD partitions the body; specs only name the manual pp axis.
    batch_spec = P()

    def per_device(params_l, x):
        params = jax.tree_util.tree_map(lambda a: a[0], params_l)
        idx = jax.lax.axis_index(axis)

        def step(state, t):
            mb = jax.lax.dynamic_index_in_dim(x, jnp.clip(t, 0, M - 1), 0,
                                              keepdims=False)
            cur = jnp.where(idx == 0, mb, state)
            out = fn(params, cur)
            perm = [(i, (i + 1) % S) for i in range(S)]
            nxt = jax.lax.ppermute(out, axis, perm)
            return nxt, out

        _, outs = jax.lax.scan(step, jnp.zeros_like(x[0]), jnp.arange(T))
        y = outs[S - 1:]                       # [M, mb, ...] valid on last stage
        y = jnp.where(idx == S - 1, y, jnp.zeros_like(y))
        return jax.lax.psum(y, axis)           # replicate last stage's outputs

    spec_params = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    # manual over the pipeline axis only: dp/mp/sharding axes stay automatic, so
    # GSPMD partitions the stage body (TP matmuls, dp batch) inside the ring.
    return shard_map(per_device, mesh=jmesh,
                     in_specs=(spec_params, batch_spec),
                     out_specs=batch_spec, axis_names={axis},
                     check_vma=False)(stacked_params, x_mb)


def interleaved_pipeline(stage_fn, stacked_params, x_mb, mesh, axis="pp",
                         num_chunks=2, remat=False):
    """Interleaved (VPP) schedule: each device owns `num_chunks` non-adjacent model
    chunks (reference: PipelineParallelWithInterleave, pipeline_parallel.py:1308).
    Param leaves are [S*num_chunks, ...] in ring order; the ring is traversed
    num_chunks times per microbatch."""
    jmesh = mesh.jax_mesh() if hasattr(mesh, "jax_mesh") else mesh
    S = jmesh.shape[axis]
    V = num_chunks
    M = x_mb.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    batch_spec = P()

    def per_device(params_l, x):
        # leaf [V, ...]: chunk v on this device is global stage (v*S + idx)
        idx = jax.lax.axis_index(axis)

        def run_ring(carry_x, v):
            # leaf local shape [V, 1(pp-local), L, ...]: pick chunk v, drop pp dim
            chunk_params = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, v, 0, keepdims=False)[0],
                params_l)
            T = M + S - 1

            def step(state, t):
                mb = jax.lax.dynamic_index_in_dim(carry_x, jnp.clip(t, 0, M - 1), 0,
                                                  keepdims=False)
                cur = jnp.where(idx == 0, mb, state)
                out = fn(chunk_params, cur)
                perm = [(i, (i + 1) % S) for i in range(S)]
                return jax.lax.ppermute(out, axis, perm), out

            _, outs = jax.lax.scan(step, jnp.zeros_like(carry_x[0]), jnp.arange(T))
            y = outs[S - 1:]
            y = jnp.where(idx == S - 1, y, jnp.zeros_like(y))
            return jax.lax.psum(y, axis), None

        y, _ = jax.lax.scan(run_ring, x, jnp.arange(V))
        return y

    spec_params = jax.tree_util.tree_map(lambda _: P(None, axis), stacked_params)

    # reshape leaves [S*V, ...] -> [V, S, ...] so chunk-major scan + pp shard works
    def reshape_leaf(a):
        return a.reshape((V, S) + a.shape[1:])

    stacked_vs = jax.tree_util.tree_map(reshape_leaf, stacked_params)
    return shard_map(per_device, mesh=jmesh,
                     in_specs=(spec_params, batch_spec),
                     out_specs=batch_spec, axis_names={axis},
                     check_vma=False)(stacked_vs, x_mb)
