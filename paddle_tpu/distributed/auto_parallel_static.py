"""Semi-auto "static" surface: Strategy / DistModel / to_static + the
remaining DTensor conveniences (LocalLayer, shard_dataloader, shard_scaler,
dtensor_from_fn, unshard_dtensor, set_mesh/get_mesh, DistAttr).

Reference: python/paddle/distributed/auto_parallel/api.py (Strategy:1973,
DistModel:2263, to_static:2988, shard_dataloader:3514), local_layer.py:27,
static/engine.py. TPU-native: "to_static" = trace the whole train step under
jax.jit with the parameters' NamedShardings (GSPMD partitions it — the analog
of the reference's mix_to_dist → partition → reshard PIR pass pipeline);
DistModel's modes select which jitted program runs (the Plan/Job analog).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import jax

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .mesh import ProcessMesh, Replicate, Shard
from .api import (
    shard_tensor, is_dist_tensor, full_value, dtensor_from_local,
)

_GLOBAL_MESH = None


def set_mesh(mesh):
    """reference: auto_parallel/api.py set_mesh — process-global default mesh."""
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh():
    return _GLOBAL_MESH


@dataclass
class DistAttr:
    """Legacy DistAttr descriptor (reference: auto_parallel DistAttr — mesh +
    per-dim sharding specs)."""
    mesh: ProcessMesh = None
    sharding_specs: list = None

    @property
    def process_mesh(self):
        return self.mesh

    def placements(self):
        names = self.mesh.dim_names if self.mesh else []
        out = [Replicate() for _ in names]
        for dim, spec in enumerate(self.sharding_specs or []):
            if spec is not None:
                out[names.index(spec)] = Shard(dim)
        return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """reference: api.py dtensor_from_fn — build then shard."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def unshard_dtensor(dist_tensor):
    """reference: api.py unshard_dtensor — back to a dense replicated Tensor."""
    if not is_dist_tensor(dist_tensor):
        return dist_tensor
    return Tensor(full_value(dist_tensor),
                  stop_gradient=dist_tensor.stop_gradient,
                  name=dist_tensor.name)


class LocalLayer(Layer):
    """Escape hatch for per-rank custom code (reference: local_layer.py:27):
    inputs are unwrapped to locals before forward, outputs re-wrapped with the
    declared dist attributes."""

    def __init__(self, out_dist_attrs, grad_dist_attrs=None):
        super().__init__()
        self.out_dist_attrs = out_dist_attrs
        self.grad_dist_attrs = grad_dist_attrs

    def __call__(self, *inputs, **kwargs):
        locals_in = [Tensor(x._value, stop_gradient=x.stop_gradient)
                     if isinstance(x, Tensor) else x for x in inputs]
        outs = super().__call__(*locals_in, **kwargs)
        single = not isinstance(outs, (list, tuple))
        outs_list = [outs] if single else list(outs)
        wrapped = []
        for i, o in enumerate(outs_list):
            if i < len(self.out_dist_attrs) and isinstance(o, Tensor):
                mesh, placements = self.out_dist_attrs[i]
                wrapped.append(dtensor_from_local(o, mesh, placements))
            else:
                wrapped.append(o)
        return wrapped[0] if single else type(outs)(wrapped)


class _Config:
    """attribute-bag with defaults (reference: auto_parallel/constants.py
    config groups feed the 249-field DistributedStrategy proto)."""

    def __init__(self, _overrides=None, **defaults):
        self.__dict__.update(defaults)
        self.__dict__.update(_overrides or {})

    def __repr__(self):
        return f"_Config({self.__dict__})"


class Strategy:
    """reference: auto_parallel/api.py:1973 Strategy — grouped knobs for the
    parallelization passes. The groups map onto our TPU lowering: sharding →
    ZeRO shard_fn stage, amp → dtype policy, pipeline → microbatch loop,
    recompute → jax.checkpoint segments, fused_passes → XLA fusion (always on).
    """

    def __init__(self, config=None):
        config = config or {}
        self.sharding = _Config(enable=False, stage=1, degree=8,
                                _overrides=config.get("sharding"))
        self.amp = _Config(enable=False, dtype="float16", level="o1",
                           init_loss_scaling=32768.0,
                           _overrides=config.get("amp"))
        self.pipeline = _Config(enable=False, schedule_mode="1F1B",
                                micro_batch_size=1, accumulate_steps=1,
                                _overrides=config.get("pipeline"))
        self.recompute = _Config(enable=False, sr=0, refined_ops_patterns=[],
                                 _overrides=config.get("recompute"))
        self.gradient_merge = _Config(enable=False, k_steps=1, avg=True,
                                      _overrides=config.get("gradient_merge"))
        self.fused_passes = _Config(enable=False, fused_passes_list=[],
                                    _overrides=config.get("fused_passes"))
        self.dataset = _Config(_overrides=config.get("dataset"))

    def __repr__(self):
        return (f"Strategy(sharding={self.sharding}, amp={self.amp}, "
                f"pipeline={self.pipeline}, recompute={self.recompute})")


class DistModel:
    """reference: api.py:2263 DistModel — the to_static product. Holds one
    jitted program per mode (train/eval/predict); __call__ runs the current
    mode's program on the batch. GSPMD shards the traced step by the
    parameters'/inputs' NamedShardings."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        self.network = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        self._mode = "train" if (loss is not None and optimizer is not None) \
            else ("eval" if loss is not None else "predict")
        self._train_step = None
        self._eval_fn = None

    # -- mode switches (reference keeps the same three) ----------------------
    def train(self):
        if self._loss is None or self._optimizer is None:
            raise ValueError(
                "loss and optimizer are required for training mode")
        self._mode = "train"
        self.network.train()
        return self

    def eval(self):
        if self._loss is None:
            raise ValueError("loss is required for eval mode")
        self._mode = "eval"
        self.network.eval()
        return self

    def predict(self):
        self._mode = "predict"
        self.network.eval()
        return self

    @property
    def mode(self):
        return self._mode

    def _split_batch(self, args):
        if self._loss is None or len(args) < 2:
            return args, ()
        return args[:-1], (args[-1],)

    def _loss_of(self, out, labels):
        if labels:
            return self._loss(out, *labels)
        return self._loss(out)

    def __call__(self, *args):
        args = tuple(a if isinstance(a, Tensor) else Tensor(a) for a in args)
        if self._mode == "train":
            if self._train_step is None:
                from ..jit.api import TrainStep

                def loss_fn(model, *batch):
                    inputs, labels = self._split_batch(batch)
                    out = model(*inputs)
                    return self._loss_of(out, labels)

                recompute = self._strategy.recompute.enable
                self._train_step = TrainStep(self.network, loss_fn,
                                             self._optimizer)
                if recompute:
                    # recompute segments are configured on the layers
                    # themselves (distributed/fleet/recompute.py)
                    pass
            return self._train_step(*args)
        if self._mode == "eval":
            inputs, labels = self._split_batch(args)
            out = self.network(*inputs)
            return self._loss_of(out, labels)
        return self.network(*args)

    # -- checkpoint surface ---------------------------------------------------
    def state_dict(self, mode="all"):
        sd = dict(self.network.state_dict())
        if mode in ("all", "opt") and self._optimizer is not None:
            sd.update({k: v for k, v in self._optimizer.state_dict().items()
                       if isinstance(v, Tensor)})
        return sd

    def set_state_dict(self, state_dict):
        self.network.set_state_dict(state_dict)
        if self._optimizer is not None:
            self._optimizer.set_state_dict(state_dict)

    def dist_main_program(self, mode=None):
        """The lowered per-mode program (jaxpr text — the PIR analog)."""
        return None


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              input_spec=None):
    """reference: api.py:2988 dist.to_static → DistModel."""
    return DistModel(layer, loader, loss, optimizer, strategy)


class ShardDataloader:
    """reference: api.py:3514 shard_dataloader — wrap an iterable so every
    yielded tensor becomes a DistTensor on `meshes`, sharded on shard_dims."""

    def __init__(self, dataloader, meshes, input_keys=None, shard_dims=None,
                 is_dataset_splitted=False):
        self._loader = dataloader
        self._meshes = meshes if isinstance(meshes, (list, tuple)) else [meshes]
        self._input_keys = input_keys
        self._shard_dims = shard_dims
        self.batch_sampler = getattr(dataloader, "batch_sampler", None)

    def __len__(self):
        return len(self._loader)

    def _placements_for(self, mesh):
        dims = self._shard_dims
        if dims is None:
            return [Replicate() for _ in range(mesh.ndim)]
        if isinstance(dims, str):
            return [Shard(0) if n == dims else Replicate()
                    for n in mesh.dim_names]
        if isinstance(dims, int):
            return [Shard(0) if i == dims else Replicate()
                    for i in range(mesh.ndim)]
        return list(dims)

    def _wrap(self, item, mesh):
        placements = self._placements_for(mesh)
        if isinstance(item, Tensor):
            return shard_tensor(item, mesh, placements)
        if isinstance(item, (list, tuple)):
            return type(item)(self._wrap(x, mesh) for x in item)
        if isinstance(item, dict):
            return {k: self._wrap(v, mesh) for k, v in item.items()}
        if isinstance(item, (np.ndarray, jax.Array)):
            return shard_tensor(Tensor(item), mesh, placements)
        return item

    def __iter__(self):
        meshes = self._meshes
        keys = self._input_keys
        for batch in self._loader:
            if len(meshes) > 1:
                # pipeline-style: element i (or input_keys[i]) -> meshes[i]
                if keys is not None and isinstance(batch, dict):
                    yield {k: self._wrap(batch[k], meshes[min(i,
                                                              len(meshes) - 1)])
                           for i, k in enumerate(keys)}
                    continue
                if isinstance(batch, (list, tuple)) and \
                        len(batch) == len(meshes):
                    yield type(batch)(self._wrap(x, m)
                                      for x, m in zip(batch, meshes))
                    continue
                raise NotImplementedError(
                    "multiple meshes need input_keys (dict batches) or a "
                    "batch with one element per mesh")
            yield self._wrap(batch, meshes[0])


def shard_dataloader(dataloader, meshes, input_keys=None, shard_dims=None,
                     is_dataset_splitted=False):
    return ShardDataloader(dataloader, meshes, input_keys, shard_dims,
                           is_dataset_splitted)


def shard_scaler(scaler):
    """reference: api.py shard_scaler — the found-inf reduction across ranks.
    Our GradScaler's found-inf check runs on the global view (XLA reduces it),
    so the scaler is already mesh-correct; returned unchanged."""
    return scaler
