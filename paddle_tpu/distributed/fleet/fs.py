"""Fleet filesystem clients: LocalFS / HDFSClient (+ DistributedInfer).

Reference: python/paddle/distributed/fleet/utils/fs.py — a uniform FS API the
PS trainers use for checkpoints and data files; HDFSClient shells out to the
hadoop CLI. Local filesystem is fully supported; HDFS operations require a
hadoop binary and raise otherwise.
"""
from __future__ import annotations

import os
import shutil
import subprocess


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class LocalFS:
    """reference: fs.py LocalFS."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for f in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, f)):
                dirs.append(f)
            else:
                files.append(f)
        return dirs, files

    def mkdirs(self, fs_path):
        assert not os.path.isfile(fs_path), f"{fs_path} is already a file"
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if os.path.isfile(fs_path):
            os.remove(fs_path)
        elif os.path.isdir(fs_path):
            shutil.rmtree(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        open(fs_path, "a").close()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not overwrite and os.path.exists(dst_path):
            raise FSFileExistsError(dst_path)
        os.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [f for f in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, f))]


class HDFSClient:
    """reference: fs.py HDFSClient — wraps the `hadoop fs` CLI. Every method
    shells out; a missing hadoop binary raises ExecuteError."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._base = [os.path.join(hadoop_home, "bin", "hadoop"), "fs"]
        if configs:
            for k, v in configs.items():
                self._base += ["-D", f"{k}={v}"]

    def _run(self, *args):
        try:
            out = subprocess.run(self._base + list(args), capture_output=True,
                                 text=True, check=False)
        except FileNotFoundError as e:
            raise ExecuteError(
                f"hadoop CLI not found: {self._base[0]}") from e
        if out.returncode != 0:
            raise ExecuteError(out.stderr.strip())
        return out.stdout

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        try:
            self._run("-test", "-f", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        self._run("-mv", fs_src_path, fs_dst_path)

    def need_upload_download(self):
        return True


class DistributedInfer:
    """PS-style distributed inference helper (reference:
    fleet/utils/ps_util.py DistributedInfer): pulls the sparse tables into
    the local program for inference. Our PS analog keeps tables in
    incubate.distributed.ps servers; init_distributed_infer_env snapshots
    them locally."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program
        self._snapshot = None

    def init_distributed_infer_env(self, exe=None, loss=None, role_maker=None,
                                   dirname=None):
        if dirname is not None:
            from ...static import load_program_state
            self._snapshot = load_program_state(dirname)

    def get_dist_infer_program(self):
        return self._main
