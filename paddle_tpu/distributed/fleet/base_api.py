"""Fleet base classes: Fleet facade, UtilBase, role makers, data generators.

Reference: python/paddle/distributed/fleet/{fleet.py Fleet,
base/util_factory.py UtilBase, base/role_maker.py Role/UserDefinedRoleMaker/
PaddleCloudRoleMaker, data_generator/data_generator.py MultiSlot*}.
"""
from __future__ import annotations

import os
import sys


class Role:
    """reference: base/role_maker.py Role enum."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class UserDefinedRoleMaker:
    """Explicit role assignment (reference: role_maker.py
    UserDefinedRoleMaker)."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        self._is_collective = is_collective
        self._current_id = kwargs.get("current_id", 0)
        self._role = kwargs.get("role", Role.WORKER)
        self._worker_endpoints = kwargs.get("worker_endpoints", [])
        self._server_endpoints = kwargs.get("server_endpoints", [])

    def worker_index(self):
        return self._current_id

    def worker_num(self):
        return max(1, len(self._worker_endpoints))

    def server_num(self):
        return len(self._server_endpoints)

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints


class PaddleCloudRoleMaker(UserDefinedRoleMaker):
    """Role from PADDLE_* env (reference: role_maker.py
    PaddleCloudRoleMaker — what fleet.init uses by default)."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        pservers = os.getenv("PADDLE_PSERVERS_IP_PORT_LIST", "")
        training_role = os.getenv("TRAINING_ROLE", "TRAINER")
        super().__init__(
            is_collective=is_collective,
            current_id=int(os.getenv("PADDLE_TRAINER_ID", "0")),
            role=Role.WORKER if training_role == "TRAINER" else Role.SERVER,
            worker_endpoints=eps.split(",") if eps else [],
            server_endpoints=pservers.split(",") if pservers else [])


class UtilBase:
    """Cross-worker utilities (reference: base/util_factory.py UtilBase —
    all_reduce/all_gather of host values, filesystem helpers)."""

    def __init__(self, role_maker=None):
        self.role_maker = role_maker

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        from ..collective import all_reduce as _ar  # host path: world==1 noop
        from ..env import get_world_size
        if get_world_size() <= 1:
            return input
        from .metrics import sum as _msum, max as _mmax, min as _mmin
        fn = {"sum": _msum, "max": _mmax, "min": _mmin}[mode]
        return fn(input)

    def all_gather(self, input, comm_world="worker"):
        from ..collective import all_gather_object
        out = []
        all_gather_object(out, input)
        return out

    def barrier(self, comm_world="worker"):
        from ..env import barrier
        barrier()

    def get_file_shard(self, files):
        """Split a file list evenly across workers (reference:
        util_factory.get_file_shard)."""
        rm = self.role_maker
        idx = rm.worker_index() if rm else 0
        n = rm.worker_num() if rm else 1
        per = len(files) // n
        rem = len(files) % n
        start = per * idx + min(idx, rem)
        end = start + per + (1 if idx < rem else 0)
        return files[start:end]

    def print_on_rank(self, message, rank_id=0):
        rm = self.role_maker
        if (rm.worker_index() if rm else 0) == rank_id:
            print(message)


class DataGenerator:
    """Line-to-slots training-data generator (reference:
    data_generator/data_generator.py DataGenerator): subclass implements
    generate_sample(line) -> iterator of (slot_name, values) lists;
    run_from_stdin streams the pipe_command protocol used by the fleet
    datasets."""

    def __init__(self):
        self.batch_size_ = 32
        self._proto_info = None

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        raise NotImplementedError(
            "Please rewrite this function to return a list or generator")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            line_iter = self.generate_sample(line)
            for user_parsed_line in line_iter():
                if user_parsed_line is None:
                    continue
                sys.stdout.write(self._gen_str(user_parsed_line))

    def run_from_memory(self, memory_data):
        out = []
        for line in memory_data:
            for parsed in self.generate_sample(line)():
                if parsed is not None:
                    out.append(self._gen_str(parsed))
        return out


class MultiSlotDataGenerator(DataGenerator):
    """slot:count:values text protocol (reference: MultiSlotDataGenerator
    _gen_str — `count v1 v2 ...` per slot, tab-free space-joined)."""

    def _gen_str(self, line):
        output = ""
        if self._proto_info is None:
            self._proto_info = [name for name, _ in line]
        for i, (name, elements) in enumerate(line):
            if output:
                output += " "
            output += str(len(elements))
            for e in elements:
                output += " " + str(e)
        return output + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, line):
        output = ""
        for i, (name, elements) in enumerate(line):
            if output:
                output += " "
            output += str(len(elements))
            for e in elements:
                output += " " + str(e)
        return output + "\n"


class Fleet:
    """The Fleet facade class (reference: fleet/fleet.py Fleet — the module-
    level fleet API is a singleton of this). Binds the module functions so
    `Fleet().init(...)` and `fleet.init(...)` share state."""

    def __init__(self):
        from . import (init, distributed_model, distributed_optimizer,
                       worker_index, worker_num, is_first_worker,
                       barrier_worker)
        self.init = init
        self.distributed_model = distributed_model
        self.distributed_optimizer = distributed_optimizer
        self.worker_index = worker_index
        self.worker_num = worker_num
        self.is_first_worker = is_first_worker
        self.barrier_worker = barrier_worker
        self.util = UtilBase()
