"""Fleet — hybrid-parallel orchestration (reference: fleet/fleet.py:218 init,
_init_hybrid_parallel_env:674; DistributedStrategy protobuf with hybrid_configs).

fleet.init builds the hybrid device mesh (dp/pp/sharding/sep/mp);
fleet.distributed_model wraps by parallel mode; fleet.distributed_optimizer adds
cross-group grad sync + hybrid clip (free under GSPMD) and ZeRO sharding.
"""
from __future__ import annotations

import numpy as np
import jax

from . import fleet_state
from .topology import CommunicateTopology, HybridCommunicateGroup
from .mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy, _c_identity, _c_concat, _c_split, _mp_allreduce,
)
from ..env import get_rank, get_world_size


class DistributedStrategy:
    """Config bundle (reference: 249-field distributed_strategy.proto — we keep the
    fields fleet users actually set)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
            "pp_configs": {},
        }
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    if strategy is None:
        strategy = DistributedStrategy()
    cfg = strategy.hybrid_configs
    dims = [cfg.get("dp_degree", 1), cfg.get("pp_degree", 1),
            cfg.get("sharding_degree", 1), cfg.get("sep_degree", 1),
            cfg.get("mp_degree", 1)]
    n_devices = len(jax.devices())
    need = int(np.prod(dims))
    assert need <= n_devices, \
        f"hybrid degrees {dims} need {need} devices, only {n_devices} available"
    # degrees that don't cover all devices run on a device subset (the reference
    # asserts product == world size; a subset keeps small test configs valid)
    topo = CommunicateTopology(["data", "pipe", "sharding", "sep", "model"], dims)
    hcg = HybridCommunicateGroup(topo)
    fleet_state.set_hcg(hcg)
    fleet_state.set_strategy(strategy)
    return hcg


def get_hybrid_communicate_group():
    return fleet_state.hcg()


def distributed_model(model):
    """Wrap by parallel mode (reference: fleet/model.py:33/:135-163)."""
    hcg = fleet_state.hcg()
    if hcg is None:
        init(is_collective=True)
        hcg = fleet_state.hcg()
    mode = hcg.get_parallel_mode()
    if mode == "pipeline":
        from .pipeline_parallel import PipelineParallel
        from .pp_layers import PipelineLayer
        if isinstance(model, PipelineLayer):
            return PipelineParallel(model, hcg, fleet_state.strategy())
        raise TypeError("pipeline mode needs a PipelineLayer model")
    if mode in ("model", "segment", "sharding", "data"):
        if hcg.get_data_parallel_world_size() > 1 or \
                hcg.get_sharding_parallel_world_size() > 1:
            # batch-axis sharding over dp AND the ZeRO sharding group (the
            # sharding group is data-parallel — that's what makes its grads
            # partial so stage2 can reduce-scatter them); mp/sep in-layer
            return _HybridShardedModel(model, hcg, axes=("dp", "sharding"))
        return model
    return model


class _HybridShardedModel:
    """Shards the input batch over the data-like mesh axes and passes through
    (TP layers carry their own shardings). Grad sync emerges from GSPMD.

    ``axes`` lists every mesh axis the batch dim splits over — plain dp, and
    for group-sharded (ZeRO) training also 'sharding': the reference's
    group_sharded stages ARE data parallelism over the sharding group, which
    is what makes grads partial along it (so stage2 can reduce-scatter them).
    """

    def __init__(self, model, hcg, axes=("dp",)):
        self._model = model
        self._hcg = hcg
        self._axes = tuple(axes)

    def __call__(self, *args, **kwargs):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from ...core.tensor import Tensor
        mesh = self._hcg.mesh.jax_mesh()
        axes = [a for a in self._axes if mesh.shape.get(a, 1) > 1]
        n = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
        if n <= 1:
            return self._model(*args, **kwargs)
        new_args = []
        for a in args:
            if isinstance(a, Tensor) and a.ndim >= 1 and a.shape[0] % n == 0:
                spec = [None] * a.ndim
                spec[0] = tuple(axes) if len(axes) > 1 else axes[0]
                v = jax.device_put(a._value, NamedSharding(
                    mesh, PartitionSpec(*spec)))
                new_args.append(Tensor(v, stop_gradient=a.stop_gradient))
            else:
                new_args.append(a)
        return self._model(*new_args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._model, name)


def distributed_optimizer(optimizer, strategy=None):
    hcg = fleet_state.hcg()
    strategy = strategy or fleet_state.strategy()
    if getattr(optimizer, "_IS_SHARDING_WRAPPER", False):
        return optimizer  # already wrapped (e.g. via group_sharded_parallel)
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        from .sharding_optimizer import DygraphShardingOptimizer
        stage = 1
        if strategy is not None:
            stage = int((strategy.sharding_configs or {}).get("stage", 1))
        return DygraphShardingOptimizer(optimizer, hcg, stage=stage)
    return optimizer


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


def is_first_worker():
    return get_rank() == 0


def barrier_worker():
    from ..env import barrier
    barrier()


# submodule re-exports
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer, SegmentLayers  # noqa: E402,F401
from .pipeline_parallel import PipelineParallel  # noqa: E402,F401
from .sharding_optimizer import DygraphShardingOptimizer  # noqa: E402,F401
from .recompute import recompute, recompute_sequential, recompute_hybrid  # noqa: E402,F401
from .sequence_parallel_utils import (  # noqa: E402,F401
    ScatterOp, AllGatherOp, ReduceScatterOp, ColumnSequenceParallelLinear,
    RowSequenceParallelLinear, mark_as_sequence_parallel_parameter,
)
from ...core.random import get_rng_state_tracker  # noqa: E402,F401
from .context_parallel import (  # noqa: E402,F401
    ring_flash_attention, ulysses_flash_attention, ContextParallelAttention,
    shard_zigzag, unshard_zigzag,
)
from .elastic import ElasticManager, ElasticStatus  # noqa: E402,F401
from . import metrics  # noqa: E402,F401

from .base_api import (  # noqa: E402,F401
    Fleet, UtilBase, Role, UserDefinedRoleMaker, PaddleCloudRoleMaker,
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from .fs import (  # noqa: E402,F401
    LocalFS, HDFSClient, DistributedInfer, ExecuteError, FSFileExistsError,
    FSFileNotExistsError,
)
