"""Megatron-style sequence parallelism (reference:
fleet/utils/sequence_parallel_utils.py — ScatterOp:85, AllGatherOp:111,
ReduceScatterOp:127, ColumnSequenceParallelLinear:429, RowSequenceParallelLinear:564).

TPU-native: the scatter/gather boundary ops are sharding constraints on the
sequence dim over the 'mp' axis; GSPMD turns Column(all-gather before GEMM) /
Row(reduce-scatter after) into the exact collective pair the reference hand-codes,
and XLA's collective-matmul pass overlaps them with the GEMM (the reference's
SPInnerOverlapLinear:257 analog, for free).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor, dispatch
from ...nn.layer_base import Layer
from ...nn.initializer import XavierNormal, Constant
from ...nn import functional as F
from ... import ops
from . import fleet_state


def _mesh():
    hcg = fleet_state.hcg()
    if hcg is None:
        raise RuntimeError("fleet.init first")
    return hcg.mesh


def _constrain(x, spec):
    mesh = _mesh()

    def fn(v):
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh.jax_mesh(), PartitionSpec(*spec)))
    return dispatch(fn, (x,), {}, name="sp_constraint")


def _seq_spec(ndim, seq_dim=0):
    spec = [None] * ndim
    spec[seq_dim] = "mp"
    return tuple(spec)


class ScatterOp:
    """Full seq -> seq sharded over mp (forward scatter, backward all-gather)."""

    @staticmethod
    def apply(x, axis=0):
        return _constrain(x, _seq_spec(x.ndim, axis))


class GatherOp:
    @staticmethod
    def apply(x, axis=0):
        return _constrain(x, (None,) * x.ndim)


class AllGatherOp:
    """seq-sharded -> full (forward all_gather, backward reduce_scatter)."""

    @staticmethod
    def apply(x):
        return _constrain(x, (None,) * x.ndim)


class ReduceScatterOp:
    """partial-sum full seq -> reduced seq-shard (forward reduce_scatter)."""

    @staticmethod
    def apply(x):
        return _constrain(x, _seq_spec(x.ndim, 0))


def scatter(x, axis=0):
    return ScatterOp.apply(x, axis)


def all_gather(x):
    return AllGatherOp.apply(x)


def reduce_scatter(x):
    return ReduceScatterOp.apply(x)


def mark_as_sequence_parallel_parameter(parameter):
    parameter._sequence_parallel = True


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "_sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               use_dp=False):
    pass  # grads of seq-parallel params sync through GSPMD automatically


class ColumnSequenceParallelLinear(Layer):
    """[s/mp, b, h] -> all-gather seq -> GEMM with col-sharded W -> [s, b, out/mp]."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        mesh = _mesh()
        w = self.create_parameter((in_features, out_features), attr=weight_attr,
                                  default_initializer=XavierNormal())
        w._value = jax.device_put(w._value, NamedSharding(
            mesh.jax_mesh(), PartitionSpec(None, "mp")))
        self.weight = w
        self.bias = None
        if has_bias:
            b = self.create_parameter((out_features,), is_bias=True,
                                      default_initializer=Constant(0.0))
            b._value = jax.device_put(b._value, NamedSharding(
                mesh.jax_mesh(), PartitionSpec("mp")))
            self.bias = b
        self.gather_output = gather_output

    def forward(self, x):
        x = AllGatherOp.apply(x)           # seq gather (GSPMD overlaps with GEMM)
        out = F.linear(x, self.weight, self.bias)
        spec = [None] * out.ndim
        if not self.gather_output:
            spec[-1] = "mp"
        return _constrain(out, tuple(spec))


class RowSequenceParallelLinear(Layer):
    """[s, b, in/mp] GEMM row-sharded W -> partial sums -> reduce-scatter seq."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=True, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        mesh = _mesh()
        w = self.create_parameter((in_features, out_features), attr=weight_attr,
                                  default_initializer=XavierNormal())
        w._value = jax.device_put(w._value, NamedSharding(
            mesh.jax_mesh(), PartitionSpec("mp", None)))
        self.weight = w
        self.bias = self.create_parameter((out_features,), is_bias=True,
                                          default_initializer=Constant(0.0)) \
            if has_bias else None

    def forward(self, x):
        spec_in = [None] * x.ndim
        spec_in[-1] = "mp"
        x = _constrain(x, tuple(spec_in))
        out = ops.matmul(x, self.weight)
        out = ReduceScatterOp.apply(out)   # reduce over mp + scatter seq dim
        if self.bias is not None:
            out = out + self.bias
        return out
