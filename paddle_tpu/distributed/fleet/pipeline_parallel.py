"""PipelineParallel runtime (reference: fleet/meta_parallel/pipeline_parallel.py:242
— train_batch drives the 1F1B/interleave schedules over NCCL p2p).

TPU-native: train_batch compiles ONE program per batch shape containing
prefix (embed) -> SPMD ring pipeline over the repeating blocks -> suffix (head+loss)
-> backward (autodiff reverse pipeline) -> optimizer update. Stage p2p is ppermute
over ICI inside the compiled program; there is no host-side schedule loop to drive.

The repeating block structure is detected from the built layers: the longest
contiguous run of structurally-identical layers is the pipeline body (must divide
evenly by pp degree x virtual chunks); everything before/after runs replicated on
all pp ranks (the reference instead places them on first/last stage — on TPU the
redundant embed/head compute is cheaper than idling the ring).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, functional_mode
from ...core import random as _random
from ...nn.layer_base import Layer, Parameter
from ...jit.functional_call import bind_state, collect_state, read_values
from ..pipeline import (spmd_pipeline, interleaved_pipeline,
                        scheduled_pipeline, scheduled_interleaved_pipeline)
from .pp_layers import PipelineLayer


def _signature(layer: Layer):
    return (type(layer).__name__,
            tuple((n, tuple(p.shape), str(p.dtype))
                  for n, p in layer.named_parameters()))


class PipelineParallel:
    def __init__(self, layers: PipelineLayer, hcg, strategy):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._S = hcg.get_pipe_parallel_world_size()
        self._V = layers._num_virtual_pipeline_stages
        self._dp = hcg.get_data_parallel_world_size()
        self._mesh = hcg.mesh
        self._accumulate_steps = (strategy.pipeline_configs.get("accumulate_steps", 1)
                                  if strategy else 1)
        # batch splits over dp AND the ZeRO sharding group (the sharding
        # group is data-parallel; its grads must be partial for stage2)
        self._batch_axes = tuple(
            a for a, deg in (("dp", self._dp),
                             ("sharding",
                              hcg.get_sharding_parallel_world_size()))
            if deg > 1)
        self._remat = layers._recompute_interval > 0
        # schedule_mode (reference: passes/pipeline_scheduler_pass/
        # pipeline_{fthenb,1f1b,eager_1f1b,vpp,zero_bubble}.py). Distinct
        # compiled runtimes, not aliases:
        # - FTHENB (and the no-mode default): whole-scan autodiff
        #   (distributed/pipeline.py spmd_pipeline) — every microbatch's
        #   intermediates stay live; optional remat per the model's own
        #   recompute config.
        # - 1F1B / EAGER1F1B: scheduled_pipeline — hand-scheduled reverse
        #   ring via custom_vjp; per-device residency = M stage-boundary
        #   activations + ONE microbatch's recompute, the 1F1B bound.
        # - ZBH1 / ZEROBUBBLE: scheduled_pipeline(zero_bubble=True) — the
        #   W-split: dx-only on the serial ring chain, dw in a ring-free
        #   deferred pass (memory-for-bubble trade, like the reference).
        # - VPP: interleaved_pipeline virtual chunks (needs V > 1).
        # - ZBVPP: scheduled_interleaved_pipeline — the ZBH1 W-split composed
        #   with the V-chunk loop (V dx-only reverse rings + a ring-free
        #   deferred V*M dw pass).
        raw_mode = (strategy.pipeline_configs.get("schedule_mode")
                    if strategy else None)
        self._schedule_mode = (raw_mode or "FTHENB").upper().replace("-", "")
        mode = self._schedule_mode
        known = {"FTHENB", "1F1B", "EAGER1F1B", "VPP", "ZBH1", "ZBVPP",
                 "ZEROBUBBLE"}
        if mode not in known:
            raise ValueError(
                f"unknown pipeline schedule_mode {raw_mode!r}; expected "
                f"one of {sorted(known)}")
        if mode in ("VPP", "ZBVPP") and self._V <= 1:
            raise ValueError(
                f"schedule_mode {mode} needs num_virtual_pipeline_stages > 1")
        if mode in ("1F1B", "EAGER1F1B", "ZBH1", "ZEROBUBBLE") \
                and self._V > 1:
            raise ValueError(
                f"schedule_mode {mode} runs V=1; use VPP for virtual chunks")
        if raw_mode is not None and mode == "FTHENB" and self._V > 1:
            raise ValueError(
                "explicit schedule_mode FThenB conflicts with "
                "num_virtual_pipeline_stages > 1 (that model requires the "
                "interleaved VPP runtime); drop schedule_mode or use VPP")
        self._cache = {}
        self._opt_remapped = False
        self._split_layers()
        self._stack_body()

    # -- structure ------------------------------------------------------------
    def _split_layers(self):
        entries = self._layers._forward_funcs
        sigs = []
        for layer, fwd in entries:
            if isinstance(layer, Layer) and fwd is None:
                sigs.append(_signature(layer))
            else:
                sigs.append(("<fn>",))
        # longest run of identical signatures with parameters
        best = (0, 0)
        i = 0
        while i < len(sigs):
            j = i
            while j < len(sigs) and sigs[j] == sigs[i] and sigs[i][0] != "<fn>" \
                    and len(sigs[i][1]) > 0:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = max(j, i + 1)
        start, end = best
        n_body = end - start
        total = self._S * self._V
        if n_body < total or n_body % total != 0:
            raise ValueError(
                f"pipeline body of {n_body} identical layers cannot be divided "
                f"across {self._S} stages x {self._V} chunks")
        self._prefix = entries[:start]
        self._body = [e[0] for e in entries[start:end]]
        self._suffix = entries[end:]
        self._L = n_body // total  # layers per (stage x chunk)

    def _stack_body(self):
        template = self._body[0]
        names = [n for n, _ in template.named_parameters()]
        self._body_template = template
        self._body_param_names = names
        stacked = {}
        for n in names:
            leaves = []
            for layer in self._body:
                p = dict(layer.named_parameters())[n]
                leaves.append(p._value)
            # shard leading stage dim over pp; preserve any TP sharding the
            # template layer put on the weight dims (TP-inside-PP composition)
            from jax.sharding import NamedSharding, PartitionSpec
            p0_val = leaves[0]
            base = [None] * p0_val.ndim
            if isinstance(getattr(p0_val, "sharding", None), NamedSharding) \
                    and p0_val.sharding.spec is not None:
                for i, s in enumerate(tuple(p0_val.sharding.spec)):
                    if i < len(base):
                        base[i] = s
            spec = ["pp", None] + base
            stacked_shape = (self._S * self._V, self._L) + tuple(p0_val.shape)
            sharding = NamedSharding(self._mesh.jax_mesh(),
                                     PartitionSpec(*spec))
            if isinstance(p0_val, jax.ShapeDtypeStruct):
                # LazyGuard-abstract body (AOT planning on a model too large
                # to materialize): stack abstractly, placement attached
                arr = jax.ShapeDtypeStruct(stacked_shape, p0_val.dtype,
                                           sharding=sharding)
            else:
                arr = jnp.stack(leaves)  # [S*V*L, ...]
                arr = arr.reshape(stacked_shape)
                arr = jax.device_put(arr, sharding)
            p0 = dict(template.named_parameters())[n]
            sp = Parameter(arr, trainable=not p0.stop_gradient,
                           name=f"pipeline_body.{n}")
            stacked[n] = sp
        self._stacked = stacked

    def sync_to_layers(self):
        """Unstack trained body params back into the per-layer Parameters.

        Stays ON DEVICE (reshape + slice of the pp-sharded stacked array) —
        the old np.asarray round-trip copied the whole body to host and back
        on every eval_batch. No-ops when the stacked values haven't changed
        since the last sync (identity check), so eval inside a train loop
        pays nothing extra per step."""
        # hold the ARRAYS (not bare ids — a freed ArrayImpl's address can be
        # recycled, falsely matching) and compare by identity
        prev = getattr(self, "_synced_vals", None)
        if prev is not None and len(prev) == len(self._stacked) and \
                all(prev.get(n) is sp._value
                    for n, sp in self._stacked.items()):
            return
        for n, sp in self._stacked.items():
            flat = jnp.reshape(
                sp._value,
                (len(self._body),) + tuple(sp._value.shape[2:]))
            for i, layer in enumerate(self._body):
                dict(layer.named_parameters())[n]._value = flat[i]
        self._synced_vals = {n: sp._value for n, sp in self._stacked.items()}

    # -- parameters -----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        params = []
        seen = set()
        for layer, _ in self._prefix + self._suffix:
            if isinstance(layer, Layer):
                for p in layer.parameters():
                    if id(p) not in seen:
                        seen.add(id(p))
                        params.append(p)
        params.extend(self._stacked.values())
        return params

    def named_parameters(self, prefix="", include_sublayers=True):
        for i, (layer, _) in enumerate(self._prefix + self._suffix):
            if isinstance(layer, Layer):
                yield from layer.named_parameters(f"stagefix{i}")
        for n, p in self._stacked.items():
            yield f"pipeline_body.{n}", p

    def state_dict(self, *a, **k):
        self.sync_to_layers()
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, state, *a, **k):
        res = self._layers.set_state_dict(state, *a, **k)
        self._stack_body()
        self._opt_remapped = False
        return res

    def eval(self):
        self._layers.eval()
        return self

    def train(self):
        self._layers.train()
        return self

    def forward(self, x):
        return self._layers.forward(x)

    __call__ = forward

    # -- training -------------------------------------------------------------
    def _remap_optimizer(self, optimizer):
        if self._opt_remapped:
            return
        optimizer._parameter_list = self.parameters()
        optimizer._slots.clear()
        optimizer._jit_update = None
        self._opt_remapped = True

    def _stage_fn(self):
        template = self._body_template
        names = self._body_param_names
        L = self._L

        def unit(param_leaves, x):
            tensors = [dict(template.named_parameters())[n] for n in names]
            with functional_mode(), bind_state(tensors, list(param_leaves)):
                out = template(Tensor(x))
            return out._value

        def stage(params, x):
            # params: dict name -> [L, ...]
            def body(h, l):
                leaves = [jax.lax.dynamic_index_in_dim(params[n], l, 0,
                                                       keepdims=False)
                          for n in names]
                return unit(leaves, h), None
            h, _ = jax.lax.scan(body, x, jnp.arange(L))
            return h
        return stage

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._remap_optimizer(optimizer)
        x, y = data if isinstance(data, (list, tuple)) else (data, None)
        x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        y = y if y is None or isinstance(y, Tensor) else Tensor(jnp.asarray(y))

        params = self.parameters()
        trainable = [p for p in params if not p.stop_gradient]
        optimizer._ensure_slots(trainable)

        key = (tuple(x.shape), str(x.dtype),
               tuple(y.shape) if y is not None else None)
        if key not in self._cache:
            self._cache[key] = self._build_step(trainable, optimizer,
                                                y is not None)
        step_fn = self._cache[key]

        param_vals = read_values(trainable)
        slot_vals = [optimizer._slots[id(p)] for p in trainable]
        optimizer._step_count += 1
        lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
        step_i = jnp.asarray(optimizer._step_count, jnp.int32)
        rng = _random.next_key()
        args = (param_vals, slot_vals, lr, step_i, rng, x._value) + \
            ((y._value,) if y is not None else ())
        loss_val, new_pv, new_slots = step_fn(*args)
        for p, nv in zip(trainable, new_pv):
            p._value = nv
        for p, ns in zip(trainable, new_slots):
            optimizer._slots[id(p)] = ns
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss_val)

    def aot_compile(self, optimizer, x, y=None):
        """AOT-compile the scheduled train-step program WITHOUT executing it.

        ``x`` / ``y`` may be ``jax.ShapeDtypeStruct``s (shardings attached)
        and the model may be LazyGuard-abstract, so a pp x tp config too
        large to materialize still compiles and memory-checks on a virtual
        mesh — the pipeline analog of TrainStep.aot_compile. Returns the jax
        ``Compiled`` (``memory_analysis()``, ``as_text()``). Reference
        analog: the pipeline scheduler pass compiling its program before the
        first train_batch (passes/pipeline_scheduler_pass)."""
        self._remap_optimizer(optimizer)
        trainable = [p for p in self.parameters() if not p.stop_gradient]
        optimizer._ensure_slots(trainable)
        has_labels = y is not None
        step_jit = self._build_step(trainable, optimizer, has_labels)
        param_vals = read_values(trainable)
        slot_vals = [optimizer._slots[id(p)] for p in trainable]
        lr = jax.ShapeDtypeStruct((), jnp.float32)
        step_i = jax.ShapeDtypeStruct((), jnp.int32)
        rng = jax.eval_shape(lambda: jax.random.key(0))
        xv = x._value if isinstance(x, Tensor) else x
        args = (param_vals, slot_vals, lr, step_i, rng, xv)
        if has_labels:
            yv = y._value if isinstance(y, Tensor) else y
            args = args + (yv,)
        return step_jit.lower(*args).compile()

    def eval_batch(self, data, compute_loss=True):
        x, y = data if isinstance(data, (list, tuple)) else (data, None)
        out = self._forward_full(x)
        if compute_loss and y is not None:
            return self._layers.loss(out, y)
        return out

    def _forward_full(self, x):
        self.sync_to_layers()
        return self._layers.forward(x)

    def _build_step(self, trainable, optimizer, has_labels):
        M = self._accumulate_steps
        mesh = self._mesh
        stage = self._stage_fn()
        stacked_names = list(self._stacked.keys())
        stacked_ids = {id(self._stacked[n]): n for n in stacked_names}
        prefix_entries, suffix_entries = self._prefix, self._suffix
        layers_obj = self._layers
        V, remat = self._V, self._remat
        mode = self._schedule_mode
        batch_axes = self._batch_axes
        n_batch = int(np.prod([mesh.jax_mesh().shape[a]
                               for a in batch_axes])) if batch_axes else 1
        decay_flags = tuple(bool(optimizer._decay_mask(p)) for p in trainable)

        def dp_shard(a, dim):
            """Pin a batch-like dim to the data-like axes (dp + ZeRO sharding
            group) so each replica group computes its slice (GSPMD would
            otherwise keep replicated inputs replicated and every replica
            would redo the full batch; for ZeRO-2 it also makes grads partial
            over the sharding group so they reduce-scatter into shards)."""
            if n_batch <= 1 or a.shape[dim] % n_batch != 0:
                return a
            from jax.sharding import NamedSharding, PartitionSpec
            spec = [None] * a.ndim
            spec[dim] = batch_axes if len(batch_axes) > 1 else batch_axes[0]
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh.jax_mesh(), PartitionSpec(*spec)))

        def run_fix(entries, h):
            for layer, fwd in entries:
                if fwd is not None:
                    h = fwd(layer, h)
                else:
                    h = layer(h) if isinstance(layer, Layer) else layer(h)
            return h

        def step_fn(param_vals, slot_vals, lr, step_i, rng, xv, *yv):
            def loss_of(pv):
                stacked_vals = {}
                fix_tensors, fix_vals = [], []
                for p, v in zip(trainable, pv):
                    if id(p) in stacked_ids:
                        stacked_vals[stacked_ids[id(p)]] = v
                    else:
                        fix_tensors.append(p)
                        fix_vals.append(v)
                with functional_mode(), bind_state(fix_tensors, fix_vals), \
                        _random.provide_key(rng):
                    h = run_fix(prefix_entries, Tensor(dp_shard(xv, 0)))
                    hv = h._value
                    B = hv.shape[0]
                    mb = B // M
                    h_mb = dp_shard(hv.reshape((M, mb) + hv.shape[1:]), 1)
                    if V > 1 and mode == "ZBVPP":
                        # zero-bubble x interleaved: W-split composed with
                        # the chunk loop (distinct runtime, not VPP+remat)
                        y_mb = scheduled_interleaved_pipeline(
                            stage, stacked_vals, h_mb, mesh, "pp",
                            num_chunks=V)
                    elif V > 1:
                        y_mb = interleaved_pipeline(stage, stacked_vals, h_mb, mesh,
                                                    "pp", num_chunks=V,
                                                    remat=remat)
                    elif mode in ("1F1B", "EAGER1F1B"):
                        y_mb = scheduled_pipeline(stage, stacked_vals, h_mb,
                                                  mesh, "pp")
                    elif mode in ("ZBH1", "ZEROBUBBLE"):
                        y_mb = scheduled_pipeline(stage, stacked_vals, h_mb,
                                                  mesh, "pp", zero_bubble=True)
                    else:
                        y_mb = spmd_pipeline(stage, stacked_vals, h_mb, mesh, "pp",
                                             remat=remat)
                    out = Tensor(y_mb.reshape((B,) + y_mb.shape[2:]))
                    out = run_fix(suffix_entries, out)
                    if has_labels:
                        loss = layers_obj.loss(out, Tensor(dp_shard(yv[0], 0)))
                    else:
                        loss = out
                return loss._value

            loss_val, grads = jax.value_and_grad(loss_of)(list(param_vals))
            new_pv, new_slots = optimizer.apply_updates(
                list(param_vals), grads, list(slot_vals), lr, step_i, decay_flags)
            return loss_val, new_pv, new_slots

        return jax.jit(step_fn, donate_argnums=(0, 1))
