"""Tensor-parallel layers (reference: fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:49, ColumnParallelLinear:336, RowParallelLinear:543,
ParallelCrossEntropy:744; mp_ops.py _c_identity/_c_split/_mp_allreduce).

TPU-native: weights carry NamedShardings over the 'mp' mesh axis and the math is
ordinary matmul — GSPMD partitions it and inserts the identity/allreduce/allgather
collectives the reference hand-writes. Megatron semantics preserved:
- ColumnParallelLinear: W [in, out] sharded on out; output sharded (gather_output
  optionally materializes the full output = all_gather).
- RowParallelLinear: W [in, out] sharded on in; input expected sharded on features;
  output needs reduction = XLA inserts the psum.
- VocabParallelEmbedding: table sharded on vocab; out-of-shard lookups masked and
  psum'd by the partitioner.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor, dispatch
from ...nn.layer_base import Layer, Parameter
from ...nn.initializer import XavierNormal, Constant
from ...nn import functional as F
from ... import ops
from ..mesh import ProcessMesh, Shard, Replicate
from ..api import shard_tensor
from . import fleet_state


def _mp_mesh():
    hcg = fleet_state.hcg()
    if hcg is None:
        raise RuntimeError("call fleet.init(is_collective=True, strategy) first")
    return hcg.mesh


def _put(value, mesh, spec):
    return jax.device_put(value, NamedSharding(mesh.jax_mesh(),
                                               PartitionSpec(*spec)))


def _constraint(x, mesh, spec):
    """with_sharding_constraint that works eager (device_put) and traced."""
    def fn(v):
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh.jax_mesh(), PartitionSpec(*spec)))
    return dispatch(fn, (x,), {}, name="sharding_constraint")


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        mesh = _mp_mesh()
        w = self.create_parameter((num_embeddings, embedding_dim), attr=weight_attr,
                                  default_initializer=XavierNormal())
        w._value = _put(w._value, mesh, ("mp", None) if "mp" in mesh.dim_names
                        else (None, None))
        self.weight = w
        self._mesh = mesh

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return out


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.gather_output = gather_output
        mesh = _mp_mesh()
        self._mesh = mesh
        w = self.create_parameter((in_features, out_features), attr=weight_attr,
                                  default_initializer=XavierNormal())
        w._value = _put(w._value, mesh, (None, "mp"))
        self.weight = w
        if has_bias:
            b = self.create_parameter((out_features,), is_bias=True,
                                      default_initializer=Constant(0.0))
            b._value = _put(b._value, mesh, ("mp",))
            self.bias = b
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = _constraint(out, self._mesh, (None,) * out.ndim)
        else:
            spec = [None] * out.ndim
            spec[-1] = "mp"
            out = _constraint(out, self._mesh, tuple(spec))
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.input_is_parallel = input_is_parallel
        mesh = _mp_mesh()
        self._mesh = mesh
        w = self.create_parameter((in_features, out_features), attr=weight_attr,
                                  default_initializer=XavierNormal())
        w._value = _put(w._value, mesh, ("mp", None))
        self.weight = w
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True,
                                              default_initializer=Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            spec = [None] * x.ndim
            spec[-1] = "mp"
            x = _constraint(x, self._mesh, tuple(spec))
        out = ops.matmul(x, self.weight)  # contraction over sharded dim -> psum
        out = _constraint(out, self._mesh, (None,) * out.ndim)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """CE over vocab-sharded logits (reference: mp_layers.py:744 →
    c_softmax_with_cross_entropy op). The take_along_axis + logsumexp over the
    sharded vocab dim lowers to the same masked-local + allreduce pattern."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        return ops.unsqueeze(loss, -1)


# mp_ops parity (reference: fleet/layers/mpu/mp_ops.py)
def _c_identity(tensor, group=None):
    return tensor


def _c_concat(tensor, group=None):
    mesh = _mp_mesh()
    return _constraint(tensor, mesh, (None,) * tensor.ndim)


def _c_split(tensor, group=None):
    mesh = _mp_mesh()
    spec = [None] * tensor.ndim
    spec[-1] = "mp"
    return _constraint(tensor, mesh, tuple(spec))


def _mp_allreduce(tensor, group=None, use_calc_stream=True, use_model_parallel=True):
    mesh = _mp_mesh()
    return _constraint(tensor, mesh, (None,) * tensor.ndim)
