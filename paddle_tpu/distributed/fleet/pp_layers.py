"""PipelineLayer model description (reference:
fleet/meta_parallel/parallel_layers/pp_layers.py — LayerDesc:57,
SharedLayerDesc:77, SegmentLayers:93, PipelineLayer:258).

Single-controller twist: ALL stages' layers are built in this process (devices, not
processes, are the stage executors). SegmentLayers keeps the reference's
cost-balanced partition API; PipelineParallel (pipeline_parallel.py) consumes the
stage structure and stacks the repeating blocks for the SPMD pipeline.
"""
from __future__ import annotations

import re

import numpy as np

from ...nn.layer_base import Layer
from ...nn.layer.containers import LayerList
from ... import ops


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Cost-balanced stage partition (reference: pp_layers.py:93)."""

    def __init__(self, layers_desc, num_parts, method="uniform", num_virtual_pipeline_stage=None):
        self._layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method
        assert len(layers_desc) >= num_parts, \
            f"{len(layers_desc)} layers cannot fill {num_parts} stages"

    def do_segment(self):
        if self.method == "uniform":
            return self.uniform(len(self._layers_desc), self.num_parts)
        if self.method.startswith("layer:"):
            cls_name = self.method.split(":", 1)[1]
            weights = [1 if self._match(d, cls_name) else 0
                       for d in self._layers_desc]
            assert sum(weights) % self.num_parts == 0, \
                f"{sum(weights)} {cls_name} layers not divisible by {self.num_parts}"
            return self._segment_by_weights(weights)
        raise ValueError(f"unknown seg_method {self.method}")

    @staticmethod
    def _match(desc, cls_name):
        name = desc.layer_func.__name__ if isinstance(desc, LayerDesc) \
            else type(desc).__name__
        return re.search(cls_name, name) is not None

    @staticmethod
    def uniform(num_items, num_parts):
        base = num_items // num_parts
        rem = num_items % num_parts
        result = [0]
        for i in range(num_parts):
            result.append(result[-1] + base + (1 if i < rem else 0))
        return result

    def _segment_by_weights(self, weights):
        per = sum(weights) // self.num_parts
        result = [0]
        acc = 0
        for i, w in enumerate(weights):
            acc += w
            if acc == per and len(result) < self.num_parts:
                result.append(i + 1)
                acc = 0
        result.append(len(weights))
        while len(result) < self.num_parts + 1:
            result.append(len(weights))
        return result


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        from . import fleet_state
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._num_virtual_pipeline_stages = num_virtual_pipeline_stages or 1
        hcg = fleet_state.hcg()
        if num_stages is None:
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = num_stages
        self._layers_desc = list(layers)
        self._shared_layers = {}

        built = []
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared_layers:
                    self._shared_layers[d.layer_name] = d.build_layer()
                built.append((self._shared_layers[d.layer_name], d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"bad pipeline entry {d}")
        self.run_function = LayerList([l for l, _ in built
                                       if isinstance(l, Layer)])
        self._forward_funcs = built

        seg_parts = self._num_stages * self._num_virtual_pipeline_stages
        self.segment_parts = SegmentLayers(
            self._layers_desc, seg_parts, seg_method).do_segment()

    @property
    def parameters_desc(self):
        return self._layers_desc

    def get_num_stages(self):
        return self._num_stages

    def get_stage_layer_indices(self, stage):
        return list(range(self.segment_parts[stage], self.segment_parts[stage + 1]))

    def forward(self, input):
        """Sequential execution (eval / 1-stage / fallback path)."""
        x = input
        for layer, fwd in self._forward_funcs:
            if fwd is not None:
                x = fwd(layer, x)
            elif isinstance(layer, Layer):
                x = layer(x)
            else:
                x = layer(x)
        return x

    def loss(self, output, label):
        if self._loss_fn is None:
            raise RuntimeError("PipelineLayer needs loss_fn for training")
        return self._loss_fn(output, label)
