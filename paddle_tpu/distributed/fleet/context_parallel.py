"""Context parallelism — user-facing ring/Ulysses attention over the `sep` axis.

The reference's `sep` hybrid axis (fleet/base/topology.py:199,
fleet/meta_parallel/segment_parallel.py:26) only provides comm groups and leaves
sequence splitting + ring attention to out-of-tree code (PaddleNLP). Here the full
context-parallel story is in-core: zigzag sharding helpers, a functional API, and a
drop-in attention layer — all lowering to ppermute/all_to_all on ICI via shard_map.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from ...core.jax_compat import shard_map  # version-adapted (core/jax_compat.py)
from jax.sharding import Mesh, PartitionSpec as P

from ...core.tensor import Tensor, dispatch
from ...ops.kernels.ring_attention import (
    ring_attention, ulysses_attention, zigzag_positions,
)


def _resolve_mesh(mesh=None, axis_name="sep"):
    if mesh is None:
        from . import fleet_state
        h = fleet_state.hcg()
        if h is not None and axis_name in h.mesh.dim_names:
            mesh = h.mesh
    if mesh is None:
        devs = np.asarray(jax.devices(), dtype=object)
        return Mesh(devs, (axis_name,))
    if hasattr(mesh, "jax_mesh"):  # ProcessMesh
        return mesh.jax_mesh()
    return mesh


def shard_zigzag(x, n_ranks, seq_axis=1):
    """Reorder the full sequence into the zigzag layout: rank r gets chunks
    (r, 2N-1-r). Apply BEFORE sharding the sequence axis; invert with
    unshard_zigzag after gathering."""
    def fn(v):
        s = v.shape[seq_axis]
        if s % (2 * n_ranks) != 0:
            raise ValueError(
                f"zigzag layout needs seq len divisible by 2*n_ranks "
                f"({s} vs 2*{n_ranks})")
        chunks = jnp.split(v, 2 * n_ranks, axis=seq_axis)
        order = []
        for r in range(n_ranks):
            order += [chunks[r], chunks[2 * n_ranks - 1 - r]]
        return jnp.concatenate(order, axis=seq_axis)
    if isinstance(x, Tensor):
        return dispatch(fn, (x,), {}, name="shard_zigzag")
    return fn(jnp.asarray(x))


def unshard_zigzag(x, n_ranks, seq_axis=1):
    """Inverse of shard_zigzag on the gathered (full-sequence) tensor."""
    def fn(v):
        chunks = jnp.split(v, 2 * n_ranks, axis=seq_axis)
        inv = [None] * (2 * n_ranks)
        j = 0
        for r in range(n_ranks):
            inv[r] = chunks[j]; j += 1
            inv[2 * n_ranks - 1 - r] = chunks[j]; j += 1
        return jnp.concatenate(inv, axis=seq_axis)
    if isinstance(x, Tensor):
        return dispatch(fn, (x,), {}, name="unshard_zigzag")
    return fn(jnp.asarray(x))


def ring_flash_attention(query, key, value, mesh=None, axis_name="sep",
                         causal=False, scale=None, balanced=None):
    """Ring attention on FULL-SIZE [B, S, H, D] tensors; this wrapper owns the
    shard_map over `axis_name`. From inside an existing shard_map (e.g. a fused
    hybrid-parallel step), call ops.kernels.ring_attention.ring_attention on the
    per-shard arrays instead — nesting this wrapper raises a mesh-context error,
    and per-shard inputs here would be silently re-sharded to 1/N of the sequence.

    balanced=None → auto: zigzag layout for causal (uniform per-rank work).
    """
    mesh = _resolve_mesh(mesh, axis_name)
    if balanced is None:
        balanced = causal
    n = mesh.shape[axis_name]

    spec = P(None, axis_name, None, None)

    def fn(q, k, v):
        if balanced:
            q, k, v = (shard_zigzag(t, n) for t in (q, k, v))
        f = shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name, causal=causal,
                                           scale=scale, balanced=balanced),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        o = f(q, k, v)
        if balanced:
            o = unshard_zigzag(o, n)
        return o

    return dispatch(fn, (query, key, value), {}, name="ring_flash_attention")


def ulysses_flash_attention(query, key, value, mesh=None, axis_name="sep",
                            causal=False, scale=None, attn_fn=None):
    """Ulysses all-to-all attention on [B, S, H, D]; H must divide by axis size.

    attn_fn overrides the local (post-all-to-all) attention; the default is the
    Pallas flash kernel on TPU, exact fp32-softmax attention elsewhere.
    """
    mesh = _resolve_mesh(mesh, axis_name)
    spec = P(None, axis_name, None, None)

    def fn(q, k, v):
        f = shard_map(
            lambda a, b, c: ulysses_attention(a, b, c, axis_name, causal=causal,
                                              scale=scale, attn_fn=attn_fn),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            # pallas_call out_shapes carry no vma info; the flash-kernel local
            # step would fail shard_map's vma check
            check_vma=False)
        return f(q, k, v)

    return dispatch(fn, (query, key, value), {}, name="ulysses_flash_attention")


class ContextParallelAttention:
    """Drop-in SDPA replacement for models running with a sep/context axis.

    mode: "ring" (arbitrary lengths, P2P ppermute ring) or "ulysses"
    (all-to-all head swap; needs heads % sep_degree == 0).
    """

    def __init__(self, mesh=None, axis_name="sep", mode="ring", causal=True):
        if mode not in ("ring", "ulysses"):
            raise ValueError(f"unknown context-parallel mode {mode!r} "
                             "(expected 'ring' or 'ulysses')")
        self.mesh = mesh
        self.axis_name = axis_name
        self.mode = mode
        self.causal = causal

    def __call__(self, q, k, v):
        fn = (ring_flash_attention if self.mode == "ring"
              else ulysses_flash_attention)
        return fn(q, k, v, mesh=self.mesh, axis_name=self.axis_name,
                  causal=self.causal)
