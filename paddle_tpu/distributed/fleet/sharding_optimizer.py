"""ZeRO-style sharding (reference: DygraphShardingOptimizer at
fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:54 — ZeRO-1
param-group partitioning + post-update broadcast; stage2/3 in
fleet/meta_parallel/sharding/group_sharded_stage2.py / group_sharded_stage3.py,
fused flat storage in group_sharded_storage.py).

TPU-native: "sharding" is a placement policy enforced inside the compiled step,
not a host-side comm protocol. Per stage:

- **Stage 1 (os)**: every optimizer slot array (moments, master weights) lives
  Shard over the 'sharding' mesh axis — each device stores 1/N of all state.
  Grads are reduced full (all-reduce); the sharded update reads 1/N of them.
- **Stage 2 (os_g)**: additionally, gradients are constrained to the same
  sharded placement *before* the update — GSPMD turns the data-parallel grad
  reduction into a reduce-scatter into shards (the reference's overlapped
  reduce_scatter schedule), and with gradient accumulation the fp32
  accumulators persist sharded at 1/N (see TrainStep._call_accumulate).
- **Stage 3 (p_g_os)**: parameters are stored sharded too; XLA all-gathers
  each weight just before use in the forward/backward and the updated param is
  written back as shards (no step-wide full-param materialization).

Placement plan per param (``_plan_for``): the first dim divisible by the
sharding degree that no existing mesh axis (e.g. TP's 'mp') already occupies
becomes the sharding dim, preserving TP placements. Params with no such dim
are stored **flattened and zero-padded** to a multiple of N so their states
and grads still shard evenly (the analog of the reference's
group_sharded_storage fused slices) — nothing silently stays replicated; only
tensors smaller than the sharding degree fall back to replication.

New-param / slot outputs are re-constrained to their stored placements, so the
compiled HLO provably carries: sharded state inputs+outputs (1/N per-device
bytes), grad reduce-scatter for stage>=2, and no full-param state residency
for stage 3 — asserted by tests/test_hlo_contracts.py.
"""
from __future__ import annotations

import warnings
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor


class ShardPlan(NamedTuple):
    spec: object        # PartitionSpec for the (possibly flat) stored form
    flat: bool          # stored flattened+padded to pad_to
    pad_to: int         # padded flat length (0 when not flat)
    param_spec: object  # placement for the *param* output (stage3: sharded)


class AccPlacement(NamedTuple):
    """Storage contract for a persistent grad accumulator (stage>=2): where it
    lives and whether it is kept in the flat-padded stored form."""
    sharding: object    # NamedSharding
    flat: bool
    pad_to: int


def _existing_spec(value):
    sh = getattr(value, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.spec is not None:
        return tuple(sh.spec) + (None,) * (value.ndim - len(tuple(sh.spec)))
    return (None,) * getattr(value, "ndim", 0)


def _plan_for(mesh, axis, shape, existing=None):
    """Choose the sharded storage form for a tensor of `shape`.

    Returns a ShardPlan whose `spec` describes the stored slot/grad placement
    and `param_spec` the param's own stored placement (existing TP axes kept).
    """
    n = mesh.shape[axis]
    existing = tuple(existing) if existing is not None else (None,) * len(shape)
    size = int(np.prod(shape)) if shape else 1
    if any(axis == e or (isinstance(e, tuple) and axis in e)
           for e in existing):
        # param already stored sharded over `axis` (stage3): states mirror it
        return ShardPlan(PartitionSpec(*existing), False, 0,
                         PartitionSpec(*existing))
    for d, s in enumerate(shape):
        if existing[d] is None and s % n == 0 and s >= n:
            spec = list(existing)
            spec[d] = axis
            # slots/grads shard on dim d; the param itself returns to its own
            # stored placement (stage1/2: the post-update all-gather point)
            return ShardPlan(PartitionSpec(*spec), False, 0,
                             PartitionSpec(*existing))
    if size >= n:  # no divisible free dim: flat-pad storage
        pad_to = -(-size // n) * n
        return ShardPlan(PartitionSpec(axis), True, pad_to,
                         PartitionSpec(*existing))
    return ShardPlan(PartitionSpec(*((None,) * len(shape))), False, 0,
                     PartitionSpec(*existing))


def _to_stored(plan, mesh, v):
    """Eager transform of a slot array into its sharded stored form.
    Abstract (ShapeDtypeStruct) slots — from a LazyGuard model under AOT
    planning — get the same stored shape/placement without materializing."""
    if isinstance(v, jax.ShapeDtypeStruct):
        shape = (plan.pad_to,) if plan.flat else tuple(v.shape)
        sharding = (None if all(s is None for s in plan.spec)
                    else NamedSharding(mesh, plan.spec))
        return jax.ShapeDtypeStruct(shape, v.dtype, sharding=sharding)
    if plan.flat:
        flat = jnp.ravel(v)
        flat = jnp.pad(flat, (0, plan.pad_to - flat.shape[0]))
        return jax.device_put(flat, NamedSharding(mesh, plan.spec))
    if all(s is None for s in plan.spec):
        return v
    return jax.device_put(v, NamedSharding(mesh, plan.spec))


class DygraphShardingOptimizer:
    """ZeRO-1 wrapper: optimizer slot states live sharded; the update runs on
    shards inside the compiled step; updated params are re-gathered.

    stage=2 additionally reduce-scatters grads into the sharded update;
    stage=3 is composed by GroupShardedStage3 (params stored sharded)."""

    _IS_SHARDING_WRAPPER = True

    def __init__(self, optimizer, hcg=None, axis="sharding", stage=1):
        from . import fleet_state
        self._inner = optimizer
        self._hcg = hcg or fleet_state.hcg()
        self._axis = axis
        self._stage = stage
        self._plans = []      # positionally aligned with the last _ensure_slots
        self._plan_params = []
        # id-keyed view of the same plans: stable across later _ensure_slots
        # calls with a different param list (eager step() vs TrainStep mixes).
        # Values are (plan, weakref) — the entry self-deletes when the param
        # dies (the callback runs during deallocation, before the id can be
        # recycled), so the dict stays bounded and pins no dead arrays.
        self._plan_by_id: dict = {}
        # route every update entry point through the wrapper, so code holding
        # the inner optimizer (TrainStep built on it, Optimizer.step) still
        # gets the sharded update — the slots ARE stored in sharded form
        optimizer._ensure_slots = self._ensure_slots
        optimizer._traced_update = self._traced_update
        optimizer.apply_updates = self.apply_updates
        optimizer._jit_update = None

    # -- state placement ------------------------------------------------------
    def _mesh(self):
        return self._hcg.mesh.jax_mesh()

    def _ensure_slots(self, params):
        inner = self._inner
        type(inner)._ensure_slots(inner, params)
        mesh = self._mesh()
        if self._axis not in mesh.shape or mesh.shape[self._axis] <= 1:
            self._plans = [None] * len(params)
            self._plan_params = list(params)
            for p in params:
                self._remember_plan(p, None)
            return
        self._plans, self._plan_params = [], []
        for p in params:
            plan = _plan_for(mesh, self._axis, tuple(p.shape),
                             _existing_spec(p._value))
            self._plans.append(plan)
            self._plan_params.append(p)
            self._remember_plan(p, plan)
            slots = inner._slots[id(p)]
            for k, v in list(slots.items()):
                if not (isinstance(v, (jax.Array, jax.ShapeDtypeStruct))
                        and v.shape):
                    continue
                if plan.flat:
                    if v.shape != (plan.pad_to,):
                        slots[k] = _to_stored(plan, mesh, v)
                elif not self._is_stored(plan, v):
                    slots[k] = _to_stored(plan, mesh, v)

    def _remember_plan(self, p, plan):
        import weakref
        pid = id(p)
        table = self._plan_by_id
        table[pid] = (plan,
                      weakref.ref(p, lambda _r, pid=pid, table=table:
                                  table.pop(pid, None)))

    @staticmethod
    def _is_stored(plan, v):
        sh = getattr(v, "sharding", None)
        if not isinstance(sh, NamedSharding):
            return False
        have = tuple(sh.spec) + (None,) * (v.ndim - len(tuple(sh.spec)))
        want = tuple(plan.spec) + (None,) * (v.ndim - len(tuple(plan.spec)))
        return have == want

    def _plans_for(self, vals):
        # positional match must also agree on shapes — a same-length call
        # with different membership would otherwise pad/reshape wrongly
        if self._plans and len(vals) == len(self._plans) and \
                all(tuple(v.shape) == tuple(p.shape)
                    for v, p in zip(vals, self._plan_params)):
            return self._plans
        # fallback (apply_updates without a preceding ensure): derive from
        # shapes alone — correct unless a same-shaped param carries TP axes
        mesh = self._mesh()
        if self._axis not in mesh.shape or mesh.shape[self._axis] <= 1:
            return [None] * len(vals)
        return [_plan_for(mesh, self._axis, tuple(v.shape)) for v in vals]

    def _grad_placement(self, param):
        """AccPlacement for `param`'s persistent grad accumulator (stage>=2),
        or None (replicated, original shape). Used by TrainStep gradient
        accumulation. Keyed by the param object, not position — the plan list
        realigns on every _ensure_slots and positions need not match the
        caller's trainable-param ordering."""
        if self._stage < 2:
            return None
        entry = self._plan_by_id.get(id(param))
        if entry is None:
            return None
        plan = entry[0]
        if plan is None:
            return None
        if plan.flat:
            # flat-pad params accumulate in the flat stored form so the
            # accumulator still shards at 1/N (e.g. vocab-padded embeddings
            # under gradient accumulation)
            return AccPlacement(NamedSharding(self._mesh(), plan.spec),
                                True, plan.pad_to)
        if all(s is None for s in tuple(plan.spec)):
            return None
        return AccPlacement(NamedSharding(self._mesh(), plan.spec), False, 0)

    # -- the pure sharded update (runs under jit) -----------------------------
    def apply_updates(self, vals, grads, slots, lr, step, decay_flags):
        inner = self._inner
        plans = self._plans_for(vals)
        mesh = self._mesh()
        if all(pl is None for pl in plans):
            return type(inner).apply_updates(inner, vals, grads, slots, lr,
                                             step, decay_flags)
        if inner._grad_clip is not None:
            grads = inner._grad_clip.apply(vals, grads)

        t_vals, t_grads, fused_ctx = [], [], []
        for v, g, pl in zip(vals, grads, plans):
            if pl is None or g is None:
                t_vals.append(v)
                t_grads.append(g)
                fused_ctx.append(None)
                continue
            if pl.flat:
                v = jnp.pad(jnp.ravel(v), (0, pl.pad_to - v.size))
                if g.ndim != 1 or g.shape != (pl.pad_to,):
                    # grads from an AccPlacement-aware accumulator arrive
                    # already in the flat stored form
                    g = jnp.pad(jnp.ravel(g), (0, pl.pad_to - g.size))
            if self._stage >= 2 and any(s is not None for s in tuple(pl.spec)):
                # ZeRO-2: reduce the dp-partial grad directly into shards
                g = jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, pl.spec))
            t_vals.append(v)
            t_grads.append(g)
            # fused Pallas update runs shard_map-wise on the stored shards —
            # GSPMD can't partition a pallas_call, so we partition for it
            fused_ctx.append((mesh, pl.spec)
                             if any(s is not None for s in tuple(pl.spec))
                             else None)

        # inner update on the stored (sharded/flat) forms; clip already done
        saved_clip = inner._grad_clip
        inner._grad_clip = None
        try:
            new_vals, new_slots = type(inner).apply_updates(
                inner, t_vals, t_grads, slots, lr, step, decay_flags,
                fused_ctx=fused_ctx)
        finally:
            inner._grad_clip = saved_clip

        out_vals, out_slots = [], []
        for v0, nv, ns, pl in zip(vals, new_vals, new_slots, plans):
            if pl is None:
                out_vals.append(nv)
                out_slots.append(ns)
                continue
            if pl.flat:
                nv = jnp.reshape(nv[:v0.size], v0.shape)
            # param goes back to its stored placement (stage1/2: original —
            # the all-gather point; stage3: sharded, no gather emitted)
            nv = jax.lax.with_sharding_constraint(
                nv, NamedSharding(mesh, pl.param_spec))
            ns = {k: (jax.lax.with_sharding_constraint(
                          s, NamedSharding(mesh, pl.spec))
                      if isinstance(s, jax.Array) and s.shape else s)
                  for k, s in ns.items()}
            out_vals.append(nv)
            out_slots.append(ns)
        return out_vals, out_slots

    def _traced_update(self, vals, grads, slots, lr, step, decay_flags):
        return self.apply_updates(vals, grads, slots, lr, step, decay_flags)

    # -- checkpoint portability ----------------------------------------------
    def state_dict(self):
        """Slots in portable form: flat-pad storage restored to the param's
        original shape so checkpoints load under any sharding degree."""
        out = self._inner.state_dict()
        names = self._inner._param_names()
        for p, plan in zip(self._plan_params, self._plans):
            if plan is None or not plan.flat:
                continue
            pname = names.get(id(p))
            if pname is None:
                continue
            size = int(np.prod(p.shape)) if tuple(p.shape) else 1
            for key in list(out):
                if isinstance(key, str) and key.startswith(pname + "."):
                    v = out[key]
                    arr = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                    if arr.ndim == 1 and arr.shape == (plan.pad_to,):
                        out[key] = Tensor(jnp.reshape(arr[:size],
                                                      tuple(p.shape)))
        return out

    def set_state_dict(self, state):
        self._inner.set_state_dict(state)
        # re-establish the stored (sharded / flat-padded) forms under the
        # CURRENT mesh, whatever form the checkpoint carried
        mesh = self._mesh()
        for p, plan in zip(self._plan_params, self._plans):
            if plan is None:
                continue
            slots = self._inner._slots.get(id(p))
            if not slots:
                continue
            for k, v in list(slots.items()):
                if not (isinstance(v, jax.Array) and v.shape):
                    continue
                if plan.flat:
                    if v.shape != (plan.pad_to,):
                        slots[k] = _to_stored(plan, mesh, v)
                elif not self._is_stored(plan, v):
                    slots[k] = _to_stored(plan, mesh, v)

    # -- delegation -----------------------------------------------------------
    @property
    def _step_count(self):
        return self._inner._step_count

    @_step_count.setter
    def _step_count(self, v):
        # augmented assignment through the wrapper (TrainStep does
        # `opt._step_count += 1`) must reach the inner optimizer — a plain
        # attribute would shadow it and checkpoints would save step 0,
        # corrupting AdamW bias correction on resume
        self._inner._step_count = v

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        return self._inner.step()

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    @property
    def _parameter_list(self):
        return self._inner._parameter_list


class GroupShardedStage2(DygraphShardingOptimizer):
    """ZeRO-2 (reference: group_sharded_stage2.py GroupShardedStage2 —
    grad segmentation + reduce_scatter into the owning rank): grads are
    constrained to the sharded state placement inside the compiled step, so
    the dp reduction lands as reduce-scatter and persistent accumulation
    buffers (gradient merge) hold only 1/N per device."""

    def __init__(self, optimizer, hcg=None, axis="sharding"):
        super().__init__(optimizer, hcg=hcg, axis=axis, stage=2)


class GroupShardedStage3:
    """ZeRO-3 (reference: group_sharded_stage3.py — segmented param storage,
    gather-on-use, release-after-use): params are *stored* sharded over the
    sharding axis; XLA inserts the per-use all-gather in forward/backward and
    the update writes shards back (param_spec keeps the sharded placement)."""

    def __init__(self, model, optimizer=None, hcg=None, axis="sharding",
                 segment_size=2 ** 20):
        from . import fleet_state
        self._hcg = hcg or fleet_state.hcg()
        mesh = self._hcg.mesh.jax_mesh()
        n = mesh.shape[axis] if axis in mesh.shape else 1
        for name, p in model.named_parameters():
            if p.stop_gradient or n <= 1:
                continue
            plan = _plan_for(mesh, axis, tuple(p.shape),
                             _existing_spec(p._value))
            if plan.flat or all(s is None for s in tuple(plan.spec)):
                # params cannot be stored flat (forward needs the true shape);
                # loud fallback instead of a silent memory-budget surprise
                warnings.warn(
                    f"GroupShardedStage3: param {name!r} shape {tuple(p.shape)}"
                    f" has no dim divisible by sharding degree {n}; it stays "
                    f"replicated (its optimizer states still shard flat)",
                    RuntimeWarning, stacklevel=2)
                continue
            p._value = jax.device_put(
                p._value, NamedSharding(mesh, plan.spec))
        self._model = model
        self._optimizer = (DygraphShardingOptimizer(optimizer, self._hcg,
                                                    axis, stage=3)
                           if optimizer is not None else None)

    def __call__(self, *a, **k):
        return self._model(*a, **k)

    def __getattr__(self, name):
        return getattr(self._model, name)
