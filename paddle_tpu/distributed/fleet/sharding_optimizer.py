"""ZeRO-style sharding (reference: DygraphShardingOptimizer at
fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:54 — ZeRO-1
param-group partitioning + post-update broadcast; stage2/3 in
fleet/meta_parallel/sharding/group_sharded_*.py).

TPU-native: "sharding" is a placement, not a protocol. Stage 1 places optimizer
slot arrays Shard(0) over the sharding axis — each device materializes only its
1/N of every moment buffer, XLA reduce-scatters grads into the sharded update and
all-gathers updated params where needed (the reference's manual
reduce_scatter+broadcast schedule). Stage 3 additionally shards the params.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor


def _shard0(mesh, axis, value):
    """Shard dim0 over `axis` when divisible, else replicate."""
    if value.ndim == 0 or value.shape[0] % mesh.jax_mesh().shape[axis] != 0:
        return value
    spec = [None] * value.ndim
    spec[0] = axis
    return jax.device_put(value, NamedSharding(mesh.jax_mesh(),
                                               PartitionSpec(*spec)))


class DygraphShardingOptimizer:
    """Wraps an inner optimizer; slot states live Shard(0) over 'sharding'."""

    def __init__(self, optimizer, hcg=None, axis="sharding"):
        from . import fleet_state
        self._inner = optimizer
        self._hcg = hcg or fleet_state.hcg()
        self._axis = axis
        orig_ensure = optimizer._ensure_slots

        def ensure(params):
            orig_ensure(params)
            mesh = self._hcg.mesh
            for p in params:
                slots = optimizer._slots[id(p)]
                for k, v in list(slots.items()):
                    if isinstance(v, jax.Array):
                        slots[k] = _shard0(mesh, self._axis, v)

        optimizer._ensure_slots = ensure

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        return self._inner.step()

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    @property
    def _parameter_list(self):
        return self._inner._parameter_list


class GroupShardedStage2(DygraphShardingOptimizer):
    """ZeRO-2: grads+states sharded. Under GSPMD grads are never materialized
    unsharded in the compiled step when states are sharded — same placement."""


class GroupShardedStage3:
    """ZeRO-3 (reference: group_sharded_stage3.py): params sharded Shard(0) too."""

    def __init__(self, model, optimizer=None, hcg=None, axis="sharding",
                 segment_size=2 ** 20):
        from . import fleet_state
        self._hcg = hcg or fleet_state.hcg()
        mesh = self._hcg.mesh
        for p in model.parameters():
            if not p.stop_gradient:
                p._value = _shard0(mesh, axis, p._value)
        self._model = model
        self._optimizer = (DygraphShardingOptimizer(optimizer, self._hcg, axis)
                           if optimizer is not None else None)

    def __call__(self, *a, **k):
        return self._model(*a, **k)

    def __getattr__(self, name):
        return getattr(self._model, name)
