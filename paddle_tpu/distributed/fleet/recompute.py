"""Activation recomputation (gradient checkpointing).

Reference analog: python/paddle/distributed/fleet/recompute/recompute.py:128
(RecomputeFunction PyLayer — replays the forward under saved RNG state during
backward) and the user API at recompute.py:463; recompute_sequential/_hybrid in
the same package.

TPU-first design: instead of a hand-written replay PyLayer, the wrapped segment
is run through ``jax.checkpoint`` (remat). XLA then materialises only the
segment *inputs* as residuals and re-traces the forward inside the backward
pass — the same FLOPs-for-HBM trade the reference makes, but expressed to the
compiler so it can still fuse the recomputed forward with the backward ops.
RNG determinism (the reference's ``preserve_rng_state``) is free: the segment
consumes an explicit key captured at forward time, so the replay sees the same
randomness by construction.
"""
from __future__ import annotations

import jax

from ...core import random as _random
from ...core.tensor import (Tensor, dispatch, functional_mode,
                            in_functional_mode, is_grad_enabled)
from ...jit.functional_call import collect_state, bind_state


#: name → jax.checkpoint_policies member. ``None``/'full' = save nothing
#: (recompute everything); the others selectively keep MXU-expensive results.
POLICIES = {
    None: None,
    "full": None,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
}


def _is_tensor(x):
    return isinstance(x, Tensor)


def _find_layers(fn, args):
    from ...jit.api import _find_layers as find
    return find(fn, args)


def recompute(function, *args, **kwargs):
    """Run ``function(*args, **kwargs)`` without saving its intermediates.

    Drop-in analog of ``paddle.distributed.fleet.utils.recompute``. Accepted
    keyword-only extras (all others are forwarded to ``function``):

    - ``use_reentrant`` (ignored — remat has one semantics here)
    - ``preserve_rng_state`` (default True; False draws a fresh key anyway,
      determinism is still guaranteed within one call)
    - ``checkpoint_policy``: name in :data:`POLICIES` or a jax policy callable.
    """
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    policy = kwargs.pop("checkpoint_policy", None)
    if isinstance(policy, str) or policy is None:
        policy = POLICIES[policy]

    # Skip only in *eager* no-grad mode. Under functional_mode the tape is off
    # but an outer jax.grad/value_and_grad may be differentiating this very
    # trace (TrainStep, pipeline step) — jax.checkpoint must still apply there
    # or remat silently degrades to keep-all-activations.
    if not is_grad_enabled() and not in_functional_mode():
        return function(*args, **kwargs)

    layers = _find_layers(function, (args, kwargs))
    from ...nn.layer_base import Layer
    for extra in getattr(function, "_recompute_layers", ()):
        if isinstance(extra, Layer) and all(extra is not l for l in layers):
            layers.append(extra)
    _, params, _, buffers = collect_state(layers) if layers else ([], [], [], [])
    state = list(params) + list(buffers)

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs),
                                                 is_leaf=_is_tensor)
    tpos = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    # one key drawn eagerly; the remat replay folds in the same key, giving the
    # reference's preserve_rng_state semantics without saving generator state
    rng = _random.next_key()

    def segment(state_vals, rng_key, *tvals):
        rebuilt = list(leaves)
        for p, v in zip(tpos, tvals):
            rebuilt[p] = Tensor(v)
        a, k = jax.tree_util.tree_unflatten(treedef, rebuilt)
        with functional_mode(), bind_state(state, state_vals), \
                _random.provide_key(rng_key):
            out = function(*a, **k)
            # buffers mutated in-place during the forward (e.g. BatchNorm
            # running stats) must leave the traced segment as outputs
            new_bufs = [b._value for b in buffers]
        out_vals = jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=_is_tensor)
        return out_vals, new_bufs

    ckpt = jax.checkpoint(segment, policy=policy)
    out, new_bufs = dispatch(ckpt, (state, rng, *[leaves[i] for i in tpos]), {},
                             name="recompute")
    for b, nb in zip(buffers, new_bufs):
        b._value = nb._value if isinstance(nb, Tensor) else nb
    return out


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Checkpoint a Sequential-like container in segments.

    Reference analog: recompute_sequential (fleet/recompute/recompute.py) —
    splits ``functions`` (a LayerList/Sequential or list of callables) into
    ``ctx['segments']`` chunks and recomputes each chunk as one unit.
    """
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else int(ctx)
    policy = ctx.get("checkpoint_policy") if isinstance(ctx, dict) else None
    fns = list(functions)
    if not fns:
        raise ValueError("recompute_sequential needs at least one function")
    segments = max(1, min(segments, len(fns)))
    per = (len(fns) + segments - 1) // segments

    def run_chunk(chunk, *xs, **kw):
        out = xs
        for f in chunk:
            out = f(*out, **kw) if isinstance(out, tuple) else f(out, **kw)
            if not isinstance(out, tuple):
                out = (out,)
        return out[0] if len(out) == 1 else out

    out = args
    for s in range(0, len(fns), per):
        chunk = fns[s:s + per]
        if not isinstance(out, tuple):
            out = (out,)
        # bind the chunk's layers so their params flow through the remat segment
        def chunk_fn(*xs, _chunk=tuple(chunk), **kw):
            return run_chunk(_chunk, *xs, **kw)
        chunk_fn._recompute_layers = chunk  # discovered inside recompute()
        out = recompute(chunk_fn, *out, checkpoint_policy=policy, **kwargs)
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Hybrid-parallel recompute (reference: recompute_hybrid.py). Offload is a
    no-op on TPU (remat already avoids persisting activations in HBM)."""
    if isinstance(ctx, dict):
        kwargs.setdefault("checkpoint_policy", ctx.get("checkpoint_policy"))
    return recompute(function, *args, **kwargs)
