"""Global fleet state (the reference keeps this on the Fleet singleton,
fleet/fleet.py)."""
from __future__ import annotations

_hcg = None
_strategy = None


def set_hcg(hcg):
    global _hcg
    _hcg = hcg


def hcg():
    return _hcg


def set_strategy(s):
    global _strategy
    _strategy = s


def strategy():
    return _strategy
