"""Elastic training manager — node membership + relaunch policy.

Reference: ElasticManager (fleet/elastic/manager.py:125) — etcd leases/watches
for node registry (manager.py:234-261), fault-tolerant same-size restarts and
scale-in/out, relaunching the local trainer with re-ranked env.

TPU-native: the registry rides the TCPStore (native daemon) instead of etcd —
each node heartbeats a lease key; the manager watches membership, and on change
computes the new (nnodes, node_rank) and invokes the relaunch callback. Actual
device-mesh reshaping is the trainer's job on restart (jax.distributed picks up
the new env).
"""
from __future__ import annotations

import threading
import time
import uuid


class ElasticStatus:
    HOLD = "hold"        # membership stable, job running
    RESTART = "restart"  # membership changed, relaunch with new ranks
    EXIT = "exit"        # scaled below min, stop


class ElasticManager:
    def __init__(self, store, node_id=None, lease_ttl=10.0, min_nodes=1,
                 max_nodes=None, on_change=None, prefix="__elastic",
                 register=True):
        self.store = store
        self.node_id = node_id or uuid.uuid4().hex[:12]
        self.lease_ttl = lease_ttl
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.on_change = on_change
        self.prefix = prefix
        #: register=False = WATCH-ONLY: this manager observes the node
        #: registry without joining it (the launcher-controller side of the
        #: reference's watch -> relaunch loop; node agents register)
        self.register = register
        self._stop = threading.Event()
        self._hb_thread = None
        self._watch_thread = None
        self.status = ElasticStatus.HOLD
        self._members: list[str] = []

    # -- registry -----------------------------------------------------------
    def _register(self):
        # registration order comes from the store's atomic counter; each node
        # owns its private slot key, so concurrent joins cannot clobber each
        # other (no list read-modify-write)
        self.store.set(f"{self.prefix}/node/{self.node_id}", time.time())
        slot = self.store.add(f"{self.prefix}/seq", 1) - 1
        self.store.set(f"{self.prefix}/slot/{slot}", self.node_id)

    def _heartbeat(self):
        while not self._stop.wait(self.lease_ttl / 3):
            self.store.set(f"{self.prefix}/node/{self.node_id}", time.time())

    def alive_nodes(self) -> list[str]:
        """Registered nodes with a fresh lease, in stable registration order."""
        now = time.time()
        n_slots = self.store.get(f"{self.prefix}/seq") or 0
        alive = []
        for slot in range(n_slots):
            nid = self.store.get(f"{self.prefix}/slot/{slot}")
            if nid is None or nid in alive:
                continue
            ts = self.store.get(f"{self.prefix}/node/{nid}")
            if ts is not None and now - ts <= self.lease_ttl:
                alive.append(nid)
        return alive

    def node_rank(self) -> int:
        """Rank among live nodes, or -1 when this node's own lease has lapsed
        (matches the -1 the on_change payload uses for an evicted node)."""
        alive = self.alive_nodes()
        return alive.index(self.node_id) if self.node_id in alive else -1

    # -- watch loop ---------------------------------------------------------
    def _watch(self):
        while not self._stop.wait(self.lease_ttl / 2):
            alive = self.alive_nodes()
            if alive != self._members:
                old, self._members = self._members, alive
                if len(alive) < self.min_nodes:
                    self.status = ElasticStatus.EXIT
                else:
                    self.status = ElasticStatus.RESTART
                if self.on_change is not None:
                    self.on_change({"old": old, "new": alive,
                                    "status": self.status,
                                    "node_rank": (alive.index(self.node_id)
                                                  if self.node_id in alive
                                                  else -1)})

    def acknowledge(self):
        """Consumer handled the pending RESTART — return to steady state.
        Status stays latched until acknowledged so polling drivers cannot miss
        a membership change between polls."""
        if self.status == ElasticStatus.RESTART:
            self.status = ElasticStatus.HOLD

    def start(self):
        if self.register:
            self._register()
            self._hb_thread = threading.Thread(target=self._heartbeat,
                                               daemon=True)
            self._hb_thread.start()
        self._members = self.alive_nodes()
        self._watch_thread = threading.Thread(target=self._watch, daemon=True)
        self._watch_thread.start()
        return self

    def stop(self, deregister=True):
        self._stop.set()
        for t in (self._hb_thread, self._watch_thread):
            if t:
                t.join(timeout=5)
        if deregister and self.register:
            # dropping the lease is enough — alive_nodes() filters dead leases;
            # the slot entry stays (stable ordering for any rejoin history)
            self.store.delete(f"{self.prefix}/node/{self.node_id}")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
