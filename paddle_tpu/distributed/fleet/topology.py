"""Hybrid-parallel topology (reference: fleet/base/topology.py —
CommunicateTopology:70, HybridCommunicateGroup:189; 5 dims dp/pp/sharding/sep/mp).

TPU-native: the topology IS a named device mesh. Axis order follows the reference
(outer→inner: dp, pp, sharding, sep, mp) so ring-neighbor ranks match; mp rides the
innermost axis (ICI-nearest) exactly like the reference puts NVLink-near ranks in
the mp group.
"""
from __future__ import annotations

import itertools

import numpy as np
import jax

from ..mesh import ProcessMesh
from ..env import Group

_HYBRID_DIMS = ["data", "pipe", "sharding", "sep", "model"]
_SHORT = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sep",
          "model": "mp"}


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = hybrid_group_names or list(_HYBRID_DIMS)
        self._dims = list(dims or [1] * len(self._parallel_names))
        n = int(np.prod(self._dims))
        self._world = np.arange(n).reshape(self._dims)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(self._world.size)

    def get_rank(self, **kwargs):
        coords = tuple(kwargs[name] for name in self._parallel_names)
        return int(self._world[coords])

    def get_coord(self, rank):
        idx = np.argwhere(self._world == rank)[0]
        import collections
        Coord = collections.namedtuple("Coord", self._parallel_names)
        return Coord(*[int(i) for i in idx])

    def get_axis_list(self, axis_name, index):
        ax = self._parallel_names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[ax] = index
        return self._world[tuple(sl)].reshape(-1).tolist()

    def get_comm_list(self, axis_name):
        """List of rank-groups along `axis_name` (one per complement coordinate)."""
        ax = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._world, ax, -1)
        return moved.reshape(-1, self._dims[ax]).tolist()


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.nranks = topology.world_size()
        self.global_rank = 0  # single-controller: logical rank 0 drives all devices
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep")
        self._mp_degree = topology.get_dim("model")

        # the named device mesh every fleet layer shards against
        dims = [self._dp_degree, self._pp_degree, self._sharding_degree,
                self._sep_degree, self._mp_degree]
        names = ["dp", "pp", "sharding", "sep", "mp"]
        keep = [(d, n) for d, n in zip(dims, names)]
        self.mesh = ProcessMesh(
            np.arange(int(np.prod(dims))).reshape([d for d, _ in keep]),
            [n for _, n in keep])

    # -- degree queries (reference API) --------------------------------------
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # -- groups ---------------------------------------------------------------
    def _axis_group(self, axis):
        ids = self._topo.get_comm_list(
            {"dp": "data", "pp": "pipe", "sharding": "sharding", "sep": "sep",
             "mp": "model"}[axis])[0]
        return Group(ids, mesh=self.mesh, axis=axis)

    def get_data_parallel_group(self):
        return self._axis_group("dp")

    def get_model_parallel_group(self):
        return self._axis_group("mp")

    def get_pipe_parallel_group(self):
        return self._axis_group("pp")

    def get_sharding_parallel_group(self):
        return self._axis_group("sharding")

    def get_sep_parallel_group(self):
        return self._axis_group("sep")

    def get_check_parallel_group(self, *a, **k):
        return self._axis_group("mp")

    def get_model_parallel_group_src_rank(self):
        return 0

    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._mp_degree > 1 or self._sep_degree > 1:
            return "model" if self._mp_degree > 1 else "segment"
        if self._sharding_degree > 1:
            return "sharding"
        return "data"
