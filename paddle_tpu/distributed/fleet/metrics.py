"""fleet.metrics analog — cross-worker metric aggregation.

Reference: python/paddle/distributed/fleet/metrics/metric.py (sum/max/min/auc
aggregated over trainers via all_reduce). TPU-native: device values reduce
through the compiled collective path when running under a mesh; host scalars
aggregate through the TCPStore object collectives — both behind one API.
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from ..env import get_world_size
from ..collective import all_gather_object

__all__ = ["sum", "max", "min", "mean", "acc", "auc"]

_py_sum, _py_max, _py_min = sum, max, min


def _gathered(value):
    arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
    if get_world_size() <= 1:
        return [arr]
    return all_gather_object(arr)


def sum(value, scope=None, util=None):
    """Global sum over workers (reference: fleet/metrics/metric.py:30 sum)."""
    parts = _gathered(value)
    return np.asarray(parts).sum(axis=0)


def max(value, scope=None, util=None):
    parts = _gathered(value)
    return np.asarray(parts).max(axis=0)


def min(value, scope=None, util=None):
    parts = _gathered(value)
    return np.asarray(parts).min(axis=0)


def mean(value, scope=None, util=None):
    parts = _gathered(value)
    return np.asarray(parts).mean(axis=0)


def acc(correct, total, scope=None, util=None):
    """Global accuracy: sum(correct)/sum(total) across workers."""
    c = np.asarray(_gathered(correct)).sum()
    t = np.asarray(_gathered(total)).sum()
    return float(c) / float(_py_max(t, 1))


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from per-worker positive/negative histogram statistics
    (reference: metric.py auc — merges bucketed TP/FP counts)."""
    pos = np.asarray(_gathered(stat_pos)).sum(axis=0).astype(np.float64)
    neg = np.asarray(_gathered(stat_neg)).sum(axis=0).astype(np.float64)
    # buckets ordered by predicted score; ROC sweeps threshold high -> low
    tot_pos = tot_neg = 0.0
    area = 0.0
    for b in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[b]
        new_neg = tot_neg + neg[b]
        area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0 or tot_neg == 0:
        return 0.5
    return float(area / (tot_pos * tot_neg))
