"""DataParallel (reference: python/paddle/distributed/parallel.py:219 + C++
EagerReducer bucketed overlap-allreduce, fluid/distributed/collective/reducer.cc).

TPU-native: DP = batch-dim sharding under GSPMD. Wrapping a model:
- parameters are placed Replicated on a 1-d 'dp' mesh,
- inputs are sharded Shard(0) over 'dp' at __call__,
- the gradient all-reduce the reference implements with a reducer+NCCL emerges from
  XLA's partitioner (replicated params + sharded batch => psum of grads), fused and
  overlapped by the latency-hiding scheduler — no bucketing machinery to maintain.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .mesh import ProcessMesh, Shard, Replicate
from .api import shard_tensor


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, mesh=None):
        super().__init__()
        self._layers = layers
        if mesh is None:
            n = len(jax.devices())
            mesh = ProcessMesh(np.arange(n), ["dp"])
        self._mesh = mesh
        # replicate parameters over dp (broadcast analog)
        for _, sub in layers.named_sublayers(include_self=True):
            for pname, p in list(sub._parameters.items()):
                if p is None:
                    continue
                sharded = shard_tensor(p, mesh, [Replicate()] * mesh.ndim)
                sub._parameters[pname] = sharded

    def forward(self, *args, **kwargs):
        sharded_args = []
        for a in args:
            if isinstance(a, Tensor) and a.ndim >= 1 \
                    and a.shape[0] % self._mesh.shape[0] == 0:
                spec = [None] * a.ndim
                spec[0] = self._mesh.dim_names[0]
                v = jax.device_put(a._value, NamedSharding(
                    self._mesh.jax_mesh(), PartitionSpec(*spec)))
                t = Tensor(v, stop_gradient=a.stop_gradient)
                sharded_args.append(t)
            else:
                sharded_args.append(a)
        return self._layers(*sharded_args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass  # grads are globally-correct by construction under GSPMD

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
