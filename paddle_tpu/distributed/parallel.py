"""DataParallel (reference: python/paddle/distributed/parallel.py:219 + C++
EagerReducer bucketed overlap-allreduce, fluid/distributed/collective/reducer.cc).

TPU-native: DP = batch-dim sharding under GSPMD. Wrapping a model:
- parameters are placed Replicated on a 1-d 'dp' mesh,
- inputs are sharded Shard(0) over 'dp' at __call__,
- the gradient all-reduce the reference implements with a reducer+NCCL emerges from
  XLA's partitioner (replicated params + sharded batch => psum of grads), fused and
  overlapped by the latency-hiding scheduler — no bucketing machinery to maintain.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from .mesh import ProcessMesh, Shard, Replicate
from .api import shard_tensor


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, mesh=None):
        super().__init__()
        self._layers = layers
        if mesh is None:
            n = len(jax.devices())
            mesh = ProcessMesh(np.arange(n), ["dp"])
        self._mesh = mesh
        self._multiproc = jax.process_count() > 1
        if self._multiproc:
            # multi-process (one controller per host): sync parameters from
            # rank 0 — the reference's sync_params_buffers broadcast
            # (parallel.py:219). Values stay process-local (implicitly
            # replicated under jit); device_put across non-addressable
            # devices is not possible here.
            from jax.experimental import multihost_utils
            # Parameters AND persistable buffers (e.g. BN running stats),
            # matching the reference's sync_params_buffers which walks
            # _obtain_parameters_buffers — per-rank-initialized buffers
            # would otherwise silently desync ranks.
            synced_vals = [p for _, p in layers.named_parameters()]
            for _, sub in layers.named_sublayers(include_self=True):
                for bname, b in sub._buffers.items():
                    # persistable buffers only — non-persistable ones
                    # (rope tables etc.) are deterministic re-derivations
                    if b is not None and \
                            bname not in sub._non_persistable_buffer_names:
                        synced_vals.append(b)
            if synced_vals:
                synced = multihost_utils.broadcast_one_to_all(
                    [t._value for t in synced_vals])
                for t, v in zip(synced_vals, synced):
                    # broadcast_one_to_all device_gets to host numpy —
                    # re-wrap so values stay jax Arrays
                    t._value = jax.numpy.asarray(v)
        else:
            # single-controller SPMD: replicate parameters over dp
            # (broadcast analog)
            for _, sub in layers.named_sublayers(include_self=True):
                for pname, p in list(sub._parameters.items()):
                    if p is None:
                        continue
                    sharded = shard_tensor(p, mesh, [Replicate()] * mesh.ndim)
                    sub._parameters[pname] = sharded

    def _shard_batch(self, a):
        """Place one batch tensor Shard(0) over dp. Multi-process: an eager
        host array is THIS rank's local shard (the reference's per-trainer
        mini-batch) and the global array is assembled across processes; a
        traced or already-global value (e.g. from shard_local_batch before a
        TrainStep) is constrained in-graph."""
        v = a._value
        if isinstance(v, jax.core.Tracer):
            mesh = self._mesh.jax_mesh()
            spec = [None] * a.ndim
            spec[0] = self._mesh.dim_names[0]
            v = jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, PartitionSpec(*spec)))
            return Tensor(v, stop_gradient=a.stop_gradient)
        if self._multiproc and isinstance(v, jax.Array) \
                and not v.is_fully_addressable:
            return a  # already a global array in the right layout family
        return shard_local_batch(a, mesh=self._mesh,
                                 axis_name=self._mesh.dim_names[0])

    def forward(self, *args, **kwargs):
        per_proc = self._mesh.shape[0] // jax.process_count() \
            if self._multiproc else self._mesh.shape[0]
        sharded_args = []
        for a in args:
            if isinstance(a, Tensor) and a.ndim >= 1 and per_proc > 0 \
                    and a.shape[0] % per_proc == 0:
                sharded_args.append(self._shard_batch(a))
            else:
                sharded_args.append(a)
        return self._layers(*sharded_args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass  # grads are globally-correct by construction under GSPMD

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def shard_local_batch(data, mesh=None, axis_name="dp"):
    """Assemble this process's local mini-batch into the global dp-sharded
    array (the DistributedBatchSampler contract: every rank feeds its own
    shard; the global batch is their concatenation in rank order).

    Use before a compiled step (TrainStep / to_static) in multi-process
    runs — in-graph code cannot assemble cross-process arrays. Single
    process: plain Shard(0) placement. Returns a Tensor.
    """
    stop_gradient = data.stop_gradient if isinstance(data, Tensor) else True
    raw = data._value if isinstance(data, Tensor) else data
    if mesh is None:
        n = len(jax.devices())
        mesh = ProcessMesh(np.arange(n), [axis_name])
    jmesh = mesh.jax_mesh()
    ndim = getattr(raw, "ndim", None) or np.asarray(raw).ndim
    spec = [None] * ndim
    spec[0] = axis_name
    sharding = NamedSharding(jmesh, PartitionSpec(*spec))
    if jax.process_count() > 1:
        # keep host data on the host until placement — no device round-trip
        local = np.asarray(raw)
        global_shape = ((local.shape[0] * jax.process_count(),)
                        + local.shape[1:])
        v = jax.make_array_from_process_local_data(sharding, local,
                                                   global_shape)
    else:
        v = jax.device_put(raw, sharding)
    return Tensor(v, stop_gradient=stop_gradient)
