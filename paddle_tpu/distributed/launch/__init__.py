"""paddle.distributed.launch analog — multi-process/multi-node job launcher.

Reference: python/paddle/distributed/launch/ (main.py:23 entry,
controllers/collective.py:37 build_pod, controllers/master.py rank-0 KV master,
job/{job,pod,container}.py process model). TPU-native: the master is the native
TCPStore daemon (csrc/tcp_store.cc) instead of an HTTP/etcd service; on TPU pods
the normal topology is ONE process per host addressing all local chips, with
`jax.distributed.initialize` driven by the env this launcher fabricates.
"""
from .controller import (Controller, ElasticController, launch,  # noqa: F401
                         launch_elastic)
