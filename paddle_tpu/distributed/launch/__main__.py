"""CLI: python -m paddle_tpu.distributed.launch [opts] script.py [script args].

Reference: python -m paddle.distributed.launch (launch/main.py:23).
"""
from __future__ import annotations

import argparse
import sys

from .controller import Controller


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a multi-process (multi-node) training job.")
    parser.add_argument("--nproc_per_node", type=int, default=1,
                        help="trainer processes on this node (TPU: usually 1)")
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--node_rank", type=int, default=None,
                        help="this node's rank; omit for store-assigned")
    parser.add_argument("--master", type=str, default=None,
                        help="host:port of the rank-0 store master")
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("--max_restarts", type=int, default=0)
    parser.add_argument("training_script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    ctl = Controller(
        args.training_script, args.script_args,
        nproc_per_node=args.nproc_per_node, nnodes=args.nnodes,
        node_rank=args.node_rank, master=args.master, log_dir=args.log_dir,
        max_restarts=args.max_restarts)
    sys.exit(ctl.run())


if __name__ == "__main__":
    main()
