"""Launcher controller — pod/container process model over the TCPStore master.

Reference mapping (SURVEY.md §2.7 Launcher):
  build_pod (launch/controllers/collective.py:37)  -> Controller._build_pod
  HTTPMaster/ETCDMaster (controllers/master.py)    -> TCPStore master
  Container/Pod (launch/job/)                      -> _Container / Controller
  watcher (controllers/watcher.py)                 -> Controller._monitor
Per-rank env contract matches the reference: PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_MASTER (+ PADDLE_LOCAL_RANK, PADDLE_NNODES).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from ..store import TCPStore
from ..launch_utils import _free_port


class _Container:
    """One trainer process (reference: launch/job/container.py)."""

    def __init__(self, cmd, env, log_path):
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.proc = None
        self._log_f = None

    def start(self):
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
            self._log_f = open(self.log_path, "ab")
            out = self._log_f
        else:
            out = None
        self.proc = subprocess.Popen(self.cmd, env=self.env, stdout=out,
                                     stderr=subprocess.STDOUT if out else None)

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    @property
    def exit_code(self):
        return None if self.proc is None else self.proc.poll()

    def terminate(self, grace=10.0):
        if self.alive():
            self.proc.send_signal(signal.SIGTERM)
            deadline = time.time() + grace
            while self.alive() and time.time() < deadline:
                time.sleep(0.1)
            if self.alive():
                self.proc.kill()
        if self._log_f:
            self._log_f.close()
            self._log_f = None


class Controller:
    """Builds the pod for this node and supervises its containers."""

    def __init__(self, training_script, script_args=(), nproc_per_node=1,
                 nnodes=1, node_rank=None, master=None, log_dir=None,
                 max_restarts=0, python_exec=None):
        self.training_script = training_script
        self.script_args = list(script_args)
        self.nproc_per_node = nproc_per_node
        self.nnodes = nnodes
        self.log_dir = log_dir
        self.max_restarts = max_restarts
        self.python = python_exec or sys.executable
        self.containers: list[_Container] = []
        self._restarts = 0

        if master is None:
            master = f"127.0.0.1:{_free_port()}"
            self._is_master_node = True
            self.node_rank = 0 if node_rank is None else node_rank
        elif node_rank is not None:
            self._is_master_node = (node_rank == 0)
            self.node_rank = node_rank
        else:
            # dynamic ranks: whichever node can bind the master address hosts
            # the store (first-wins, like the reference's HTTPMaster on rank 0)
            self._is_master_node = None
            self.node_rank = None
        self.master = master

        host, _, port = master.partition(":")
        if self._is_master_node is None:
            try:
                self.store = TCPStore(host, int(port), is_master=True,
                                      world_size=nnodes)
                self._is_master_node = True
            except OSError:
                self.store = TCPStore(host, int(port), is_master=False,
                                      world_size=nnodes)
                self._is_master_node = False
        else:
            self.store = TCPStore(host, int(port),
                                  is_master=self._is_master_node,
                                  world_size=nnodes)
        if self.node_rank is None:
            # dynamic rank assignment through the store (ETCDMaster analog)
            self.node_rank = self.store.add("__launch/node_seq", 1) - 1

    # -- pod construction ---------------------------------------------------
    def _generation(self):
        """Store-coordinated restart generation (a per-node counter would
        desynchronize barrier/coordinator namespaces on partial restarts:
        only the master node's rebuild bumps it; a lone non-master restart
        rejoins the incumbent generation)."""
        if self._is_master_node:
            return int(self.store.add("__launch/generation", 1))
        self.store.wait("__launch/generation", timeout=60)
        return int(self.store.get("__launch/generation"))

    def _coordinator_address(self, gen):
        """Address for the jax.distributed coordination service.

        PADDLE_MASTER's port is occupied by the launcher's TCPStore, so the
        master node picks a fresh port per generation and publishes it
        through the store (the reference's NCCL-id exchange analog);
        other nodes read their generation's key — never a dead one's."""
        host, _, _ = self.master.partition(":")
        key = f"__launch/coordinator/g{gen}"
        if self._is_master_node:
            coord = f"{host}:{_free_port()}"
            self.store.set(key, coord.encode())
            return coord
        val = self.store.wait(key, timeout=60)
        return (val.decode() if isinstance(val, (bytes, bytearray))
                else str(val))

    def _build_pod(self):
        world = self.nnodes * self.nproc_per_node
        gen = self._generation()
        coordinator = self._coordinator_address(gen)
        self.containers = []
        for local in range(self.nproc_per_node):
            rank = self.node_rank * self.nproc_per_node + local
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_LOCAL_RANK": str(local),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_NNODES": str(self.nnodes),
                "PADDLE_MASTER": self.master,
                "PADDLE_COORDINATOR": coordinator,
                # restart generation: store-backed primitives (barriers)
                # namespace their keys by this so a killed generation's
                # dangling counts can't skew the relaunched one
                "PADDLE_RESTART_ID": str(gen),
            })
            # scripts outside the framework checkout must still import it:
            # prepend the launcher's import root to the workers' PYTHONPATH
            pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
            parts = [pkg_root, env.get("PYTHONPATH", "")]
            env["PYTHONPATH"] = os.pathsep.join(p for p in parts if p)
            log = (os.path.join(self.log_dir, f"workerlog.{rank}")
                   if self.log_dir else None)
            cmd = [self.python, self.training_script] + self.script_args
            self.containers.append(_Container(cmd, env, log))

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._build_pod()
        for c in self.containers:
            c.start()

    def stop(self, grace=10.0):
        """grace: seconds between SIGTERM and SIGKILL. NOTE trainers that
        ran jax.distributed.initialize CATCH SIGTERM (the runtime's
        preemption notifier treats it as a preemption signal and keeps
        running) — teardowns that must actually stop training (elastic
        reshape) pass a SHORT grace so the SIGKILL lands promptly."""
        for c in self.containers:
            c.terminate(grace=grace)

    def _raise_failed(self, failed, codes):
        """Shared failure report: first failed rank + its log tail."""
        first = self.containers[failed[0]]
        tail = ""
        if first.log_path and os.path.exists(first.log_path):
            with open(first.log_path, "rb") as f:
                tail = f.read()[-4096:].decode(errors="replace")
        raise RuntimeError(
            f"rank {failed[0]} exited with code {codes[failed[0]]}\n"
            f"--- log tail ---\n{tail}")

    def _monitor(self, poll_interval=0.5):
        """Supervise until success, failure (kill pod), or restart budget."""
        while True:
            codes = [c.exit_code for c in self.containers]
            if all(code == 0 for code in codes):
                return 0
            failed = [i for i, code in enumerate(codes)
                      if code not in (None, 0)]
            if failed:
                self.stop()
                if self._restarts < self.max_restarts:
                    self._restarts += 1
                    self.start()
                    continue
                self._raise_failed(failed, codes)
            time.sleep(poll_interval)

    def run(self):
        self.start()
        try:
            return self._monitor()
        finally:
            self.stop()


class ElasticController(Controller):
    """MANAGER-driven elastic orchestration (reference: ElasticManager's
    membership-watch -> relaunch-at-new-world-size loop,
    fleet/elastic/manager.py:234-261 — NOT test-stitched launches).

    A watch-only :class:`~paddle_tpu.distributed.fleet.elastic.ElasticManager`
    observes node-agent leases in the launcher's store; each live agent
    contributes ``nproc_per_node`` trainer slot(s) on this host (the
    single-host simulation of cluster machines). On membership change the
    CONTROLLER tears the pod down and relaunches at the new world size —
    trainers resume from their checkpoints; below ``min_nodes`` the job
    exits. Node agents join by running
    ``ElasticManager(store_client).start()`` (heartbeat lease) and leave by
    stopping it."""

    def run_elastic(self, min_nodes=1, lease_ttl=3.0, poll_interval=0.3,
                    startup_timeout=60.0):
        from ..fleet.elastic import ElasticManager, ElasticStatus
        mgr = ElasticManager(self.store, register=False, min_nodes=min_nodes,
                             lease_ttl=lease_ttl)
        deadline = time.time() + startup_timeout
        while len(mgr.alive_nodes()) < max(min_nodes, 1):
            if time.time() > deadline:
                raise RuntimeError(
                    "run_elastic: no node agents joined the registry "
                    f"within {startup_timeout}s")
            time.sleep(poll_interval)
        mgr.start()
        base_nproc = self.nproc_per_node
        try:
            while True:
                self.nproc_per_node = base_nproc * len(mgr._members)
                self.start()
                status = self._supervise_elastic(mgr, poll_interval)
                # short grace: jax.distributed workers CATCH SIGTERM (see
                # stop()) — a reshape teardown must not let the old
                # generation keep training through a long grace window
                self.stop(grace=0.5)
                if status == 0:
                    return 0
                if status == ElasticStatus.EXIT:
                    raise RuntimeError(
                        f"run_elastic: membership fell below min_nodes="
                        f"{min_nodes}; stopping")
                mgr.acknowledge()  # RESTART handled: relaunch at new size
        finally:
            # every exit path — including a budget-exhausted raise from
            # _supervise_elastic — must reap the pod (SIGTERM-immune jax
            # workers would otherwise train on as orphans)
            self.stop(grace=0.5)
            mgr.stop(deregister=False)

    def _supervise_elastic(self, mgr, poll_interval):
        from ..fleet.elastic import ElasticStatus
        while True:
            if mgr.status in (ElasticStatus.RESTART, ElasticStatus.EXIT):
                return mgr.status
            codes = [c.exit_code for c in self.containers]
            if all(code == 0 for code in codes):
                return 0
            failed = [i for i, code in enumerate(codes)
                      if code not in (None, 0)]
            if failed:
                # worker death WITHOUT a membership change: fault-tolerant
                # same-size restart from the budget (the manager loop still
                # owns any concurrent scale decision)
                if self._restarts < self.max_restarts:
                    self._restarts += 1
                    return ElasticStatus.RESTART
                self._raise_failed(failed, codes)
            time.sleep(poll_interval)


def launch(training_script, script_args=(), **kwargs):
    """Programmatic entry — returns the exit status (0 on success)."""
    return Controller(training_script, script_args, **kwargs).run()


def launch_elastic(training_script, script_args=(), min_nodes=1,
                   lease_ttl=3.0, **kwargs):
    """Elastic entry: supervise under the manager's watch->relaunch loop.
    ``nproc_per_node`` is the per-AGENT process count (world size scales
    with live agents)."""
    ctl = ElasticController(training_script, script_args, **kwargs)
    return ctl.run_elastic(min_nodes=min_nodes, lease_ttl=lease_ttl)
