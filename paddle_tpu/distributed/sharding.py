"""paddle.distributed.sharding — group_sharded_parallel API (reference:
python/paddle/distributed/sharding/group_sharded.py)."""
from __future__ import annotations


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=None,
                           segment_size=None, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """level: 'os' (ZeRO-1), 'os_g' (ZeRO-2), 'p_g_os' (ZeRO-3)."""
    from .fleet.sharding_optimizer import (
        DygraphShardingOptimizer, GroupShardedStage2, GroupShardedStage3)
    from .fleet import fleet_state
    if fleet_state.hcg() is None or \
            fleet_state.hcg().get_sharding_parallel_world_size() == 1:
        from . import fleet
        strategy = fleet.DistributedStrategy()
        import jax
        strategy.hybrid_configs["sharding_degree"] = len(jax.devices())
        fleet.init(is_collective=True, strategy=strategy)
    # group-sharded training is data-parallel over the sharding group: the
    # batch splits along it so grads are partial there (stage2's
    # reduce-scatter needs this); the wrapper is a no-op for non-dist inputs
    from .fleet import _HybridShardedModel
    from .fleet import fleet_state as _fs
    if level == "os":
        opt = DygraphShardingOptimizer(optimizer)
        return _HybridShardedModel(model, _fs.hcg(), axes=("dp", "sharding")), \
            opt, scaler
    if level == "os_g":
        opt = GroupShardedStage2(optimizer)
        return _HybridShardedModel(model, _fs.hcg(), axes=("dp", "sharding")), \
            opt, scaler
    if level == "p_g_os":
        wrapped = GroupShardedStage3(model, optimizer)
        sharded = _HybridShardedModel(wrapped, _fs.hcg(),
                                      axes=("dp", "sharding"))
        return sharded, wrapped._optimizer, scaler
    raise ValueError(f"unknown sharding level {level!r}")


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ..framework_io import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
