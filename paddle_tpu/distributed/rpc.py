"""paddle.distributed.rpc analog — simple cross-worker RPC.

Reference: paddle/fluid/distributed/rpc/ + python/paddle/distributed/rpc/
(brpc-based: init_rpc/rpc_sync/rpc_async/shutdown, WorkerInfo registry).
TPU-native: device traffic never uses RPC (collectives compile into programs);
this is the host-side control-plane analog — each worker runs a socket server
thread, the worker registry lives in the TCPStore, payloads are pickled
callables + args (callables must be importable in the callee, same contract as
the reference).
"""
from __future__ import annotations

import concurrent.futures
import os
import pickle
import socket
import struct
import threading

from .store import TCPStore, _recv_full, create_or_get_global_tcp_store


class WorkerInfo:
    def __init__(self, name, rank, host, port):
        self.name = name
        self.rank = rank
        self.host = host
        self.port = port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name}, rank={self.rank}, "
                f"ip={self.host}, port={self.port})")


class _RpcGlobal:
    store: TCPStore | None = None
    owns_store: bool = False
    server: socket.socket | None = None
    server_thread: threading.Thread | None = None
    pool: concurrent.futures.ThreadPoolExecutor | None = None
    name: str | None = None
    rank: int = -1
    world_size: int = 0
    stopping = False
    info_cache: dict | None = None


_g = _RpcGlobal()


def _send_msg(sock, payload: bytes):
    sock.sendall(struct.pack("!I", len(payload)) + payload)


def _serve_conn(conn):
    try:
        while True:
            (n,) = struct.unpack("!I", _recv_full(conn, 4))
            fn, args, kwargs = pickle.loads(_recv_full(conn, n))
            try:
                result = ("ok", fn(*args, **kwargs))
            except Exception as e:  # ship the exception back to the caller
                result = ("err", e)
            try:
                payload = pickle.dumps(result)
            except Exception as e:
                # unpicklable result/exception: ship a serializable summary so
                # the caller sees the real failure, not a ConnectionError
                import traceback
                payload = pickle.dumps(
                    ("err", RuntimeError(
                        f"rpc result not picklable ({e!r}); original "
                        f"result/exception: {result[1]!r}\n"
                        f"{traceback.format_exc()}")))
            _send_msg(conn, payload)
    except (ConnectionError, struct.error, OSError):
        pass
    finally:
        try:
            conn.close()
        except OSError:
            pass


def _server_loop(srv):
    while not _g.stopping:
        try:
            conn, _ = srv.accept()
        except OSError:
            return
        threading.Thread(target=_serve_conn, args=(conn,), daemon=True).start()


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC server and register it (reference:
    python/paddle/distributed/rpc/rpc.py init_rpc)."""
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if world_size is None:
        world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if master_endpoint is not None:
        host, _, port = master_endpoint.partition(":")
        _g.store = TCPStore(host, int(port), is_master=(rank == 0),
                            world_size=world_size)
        _g.owns_store = True
    else:
        _g.store = create_or_get_global_tcp_store()
        _g.owns_store = False

    # bind only the advertised interface (loopback by default): the payload is
    # pickled callables, so exposure beyond the training cluster's interface
    # would be remote code execution for any network peer
    host = os.environ.get("PADDLE_LOCAL_IP", "127.0.0.1")
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, 0))
    srv.listen(64)
    port = srv.getsockname()[1]
    _g.server = srv
    _g.stopping = False
    _g.server_thread = threading.Thread(target=_server_loop, args=(srv,),
                                        daemon=True)
    _g.server_thread.start()
    _g.pool = concurrent.futures.ThreadPoolExecutor(max_workers=16)
    _g.name = name
    _g.rank = rank
    _g.world_size = world_size
    _g.store.set(f"__rpc/worker/{name}",
                 {"rank": rank, "host": host, "port": port})
    _g.store.set(f"__rpc/name_by_rank/{rank}", name)
    # barrier: all workers registered before anyone issues calls
    _g.store.barrier("__rpc_init", world_size=world_size)


def get_worker_info(name=None) -> WorkerInfo:
    # the registry is immutable after the init barrier — cache per process
    name = name or _g.name
    if _g.info_cache is None:
        _g.info_cache = {}
    info = _g.info_cache.get(name)
    if info is None:
        ent = _g.store.wait(f"__rpc/worker/{name}", timeout=60)
        info = WorkerInfo(name, ent["rank"], ent["host"], ent["port"])
        _g.info_cache[name] = info
    return info


def get_all_worker_infos():
    infos = []
    for r in range(_g.world_size):
        nm = _g.store.get(f"__rpc/name_by_rank/{r}")
        if nm is not None:
            infos.append(get_worker_info(nm))
    return infos


def _call(to_name, fn, args, kwargs, timeout):
    info = get_worker_info(to_name)
    with socket.create_connection((info.host, info.port),
                                  timeout=timeout or 120) as sock:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_msg(sock, pickle.dumps((fn, args, kwargs)))
        (n,) = struct.unpack("!I", _recv_full(sock, 4))
        status, payload = pickle.loads(_recv_full(sock, n))
    if status == "err":
        raise payload
    return payload


def rpc_sync(to, fn, args=(), kwargs=None, timeout=None):
    """Blocking remote call (reference: rpc.py rpc_sync)."""
    return _call(to, fn, tuple(args), kwargs or {}, timeout)


def rpc_async(to, fn, args=(), kwargs=None, timeout=None):
    """Returns a concurrent.futures.Future (reference: rpc.py rpc_async,
    which returns a FutureWrapper with .wait())."""
    fut = _g.pool.submit(_call, to, fn, tuple(args), kwargs or {}, timeout)
    fut.wait = fut.result  # paddle calls .wait()
    return fut


def shutdown():
    """Graceful teardown: barrier so in-flight peers finish, then stop."""
    if _g.store is not None:
        try:
            _g.store.barrier("__rpc_shutdown", world_size=_g.world_size)
        except Exception:
            pass
    _g.stopping = True
    if _g.server is not None:
        try:
            _g.server.close()
        except OSError:
            pass
    if _g.pool is not None:
        _g.pool.shutdown(wait=False)
    if _g.owns_store and _g.store is not None \
            and getattr(_g.store, "_server", None) is not None:
        _g.store._server.stop()
    _g.server = None
    _g.store = None
    _g.owns_store = False
    _g.info_cache = None


def get_current_worker_info():
    """reference: distributed/rpc/__init__.py get_current_worker_info — the
    calling process's own WorkerInfo (get_worker_info defaults to it)."""
    return get_worker_info()
