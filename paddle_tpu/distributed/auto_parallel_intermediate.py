"""One-line parallelize API (reference:
distributed/auto_parallel/intermediate/parallelize.py:51 — plans in
tensor_parallel.py / pipeline_parallel.py / sharded_data_parallel.py).

parallelize(model, optimizer, mesh, config) applies, in order:
- dp_config: batch-sharding data parallel (+ ZeRO level via sharding stage)
- mp_config: per-layer sharding plan {layer_name_pattern: plan}
- pp_config: pipeline split (delegated to fleet PipelineLayer path)
"""
from __future__ import annotations

import re

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..nn.layer_base import Layer
from .mesh import ProcessMesh, Shard, Replicate
from .api import shard_tensor, shard_optimizer, ShardingStage1


class ColWiseParallel:
    """Shard weight's output dim over 'mp'."""

    def apply(self, layer, mesh):
        if getattr(layer, "weight", None) is not None:
            w = layer.weight
            w._value = jax.device_put(w._value, NamedSharding(
                mesh.jax_mesh(), PartitionSpec(None, "mp")))
        if getattr(layer, "bias", None) is not None:
            b = layer.bias
            b._value = jax.device_put(b._value, NamedSharding(
                mesh.jax_mesh(), PartitionSpec("mp")))


class RowWiseParallel:
    def apply(self, layer, mesh):
        if getattr(layer, "weight", None) is not None:
            w = layer.weight
            w._value = jax.device_put(w._value, NamedSharding(
                mesh.jax_mesh(), PartitionSpec("mp", None)))


class SequenceParallelBegin:
    def apply(self, layer, mesh):
        pass


class SequenceParallelEnd:
    def apply(self, layer, mesh):
        pass


_PLAN_MAP = {
    "ColWiseParallel": ColWiseParallel,
    "RowWiseParallel": RowWiseParallel,
}


def parallelize(model, optimizer=None, mesh=None, config=None):
    config = config or {}
    if mesh is None:
        n = len(jax.devices())
        mp = config.get("mp_config", {}).get("mp_degree") or 1
        mesh = ProcessMesh(np.arange(n).reshape(n // mp, mp), ["dp", "mp"])

    mp_cfg = config.get("mp_config") or {}
    plans = mp_cfg.get("parallelize_plan") or {}
    for pattern, plan in plans.items():
        plan_obj = plan if not isinstance(plan, str) else _PLAN_MAP[plan]()
        for name, sub in model.named_sublayers(include_self=True):
            if re.fullmatch(pattern.replace("*", ".*"), name):
                plan_obj.apply(sub, mesh)

    dp_cfg = config.get("dp_config") or {}
    if optimizer is not None and dp_cfg.get("sharding_level") in (1, 2, 3, "os"):
        optimizer = shard_optimizer(optimizer, ShardingStage1("dp", mesh))

    if optimizer is None:
        return model
    return model, optimizer
