"""One-line parallelize API (reference:
distributed/auto_parallel/intermediate/parallelize.py:51 — plans in
tensor_parallel.py / pipeline_parallel.py / sharded_data_parallel.py).

parallelize(model, optimizer, mesh, config) applies, in order:
- dp_config: batch-sharding data parallel (+ ZeRO level via sharding stage)
- mp_config: per-layer sharding plan {layer_name_pattern: plan}
- pp_config: pipeline split (delegated to fleet PipelineLayer path)
"""
from __future__ import annotations

import re

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..nn.layer_base import Layer
from .mesh import ProcessMesh, Shard, Replicate
from .api import shard_tensor, shard_optimizer, ShardingStage1


class ColWiseParallel:
    """Shard weight's output dim over 'mp'."""

    def apply(self, layer, mesh):
        if getattr(layer, "weight", None) is not None:
            w = layer.weight
            w._value = jax.device_put(w._value, NamedSharding(
                mesh.jax_mesh(), PartitionSpec(None, "mp")))
        if getattr(layer, "bias", None) is not None:
            b = layer.bias
            b._value = jax.device_put(b._value, NamedSharding(
                mesh.jax_mesh(), PartitionSpec("mp")))


class RowWiseParallel:
    def apply(self, layer, mesh):
        if getattr(layer, "weight", None) is not None:
            w = layer.weight
            w._value = jax.device_put(w._value, NamedSharding(
                mesh.jax_mesh(), PartitionSpec("mp", None)))


class SequenceParallelBegin:
    def apply(self, layer, mesh):
        pass


class SequenceParallelEnd:
    def apply(self, layer, mesh):
        pass


_PLAN_MAP = {
    "ColWiseParallel": ColWiseParallel,
    "RowWiseParallel": RowWiseParallel,
}


def parallelize(model, optimizer=None, mesh=None, config=None):
    config = config or {}
    if mesh is None:
        n = len(jax.devices())
        mp = config.get("mp_config", {}).get("mp_degree") or 1
        mesh = ProcessMesh(np.arange(n).reshape(n // mp, mp), ["dp", "mp"])

    mp_cfg = config.get("mp_config") or {}
    plans = mp_cfg.get("parallelize_plan") or {}
    for pattern, plan in plans.items():
        plan_obj = plan if not isinstance(plan, str) else _PLAN_MAP[plan]()
        for name, sub in model.named_sublayers(include_self=True):
            if re.fullmatch(pattern.replace("*", ".*"), name):
                plan_obj.apply(sub, mesh)

    dp_cfg = config.get("dp_config") or {}
    if optimizer is not None and dp_cfg.get("sharding_level") in (1, 2, 3, "os"):
        optimizer = shard_optimizer(optimizer, ShardingStage1("dp", mesh))

    if optimizer is None:
        return model
    return model, optimizer


class SequenceParallelEnable:
    """Mark a layer as fully sequence-parallel (reference:
    intermediate/sequence_parallel.py SequenceParallelEnable): activations
    shard the sequence dim over 'mp' between the Begin/End boundaries. Under
    GSPMD this is a with_sharding_constraint on the layer output."""

    def apply(self, layer, mesh):
        spec = PartitionSpec(None, "mp")

        def hook(l, inputs, outputs):
            from ..core.tensor import Tensor as _T
            if isinstance(outputs, _T) and outputs._value.ndim >= 2:
                outputs._value = jax.lax.with_sharding_constraint(
                    outputs._value, NamedSharding(mesh.jax_mesh(), spec))
            return outputs
        layer.register_forward_post_hook(hook)


class SequenceParallelDisable:
    """Opt a sub-layer out of sequence parallelism (reference:
    intermediate/sequence_parallel.py SequenceParallelDisable)."""

    def __init__(self, need_transpose=True):
        self.need_transpose = need_transpose

    def apply(self, layer, mesh):
        def pre(l, inputs):
            from ..core.tensor import Tensor as _T
            out = []
            for x in inputs:
                if isinstance(x, _T) and x._value.ndim >= 2:
                    x._value = jax.lax.with_sharding_constraint(
                        x._value,
                        NamedSharding(mesh.jax_mesh(),
                                      PartitionSpec(*([None] * x._value.ndim))))
                out.append(x)
            return tuple(out)
        layer.register_forward_pre_hook(pre)


class PrepareLayerInput:
    """Run a user fn on layer inputs (reference: intermediate/parallel_base.py
    PrepareLayerInput — used to insert reshard/redistribute points)."""

    def __init__(self, fn=None):
        self.fn = fn

    def apply(self, layer, mesh):
        if self.fn is not None:
            layer.register_forward_pre_hook(self.fn(mesh))


class PrepareLayerOutput:
    def __init__(self, fn=None):
        self.fn = fn

    def apply(self, layer, mesh):
        if self.fn is not None:
            layer.register_forward_post_hook(self.fn(mesh))


class SplitPoint:
    """Pipeline split markers (reference: intermediate/pipeline_parallel.py
    SplitPoint): BEGINNING splits before the marked layer, END after."""
    BEGINNING = "BEGINNING"
    END = "END"


def to_distributed(model, optimizer, dataloader, device_num, node_num=1,
                   config=None):
    """reference: distributed/auto_parallel/high_level_api.py to_distributed —
    pick a parallel strategy automatically from the hardware shape. Heuristic
    here (the reference's is a cost-model search): prefer dp; add mp when the
    model is too large for one device's HBM."""
    n = device_num * node_num
    params = sum(int(np.prod(p.shape)) for p in model.parameters())
    bytes_needed = params * 4 * 3  # weights + grads + adam states
    try:
        hbm = jax.devices()[0].memory_stats().get("bytes_limit", 16e9)
    except Exception:
        hbm = 16e9
    mp = 1
    while bytes_needed / mp > hbm * 0.6 and mp < n:
        mp *= 2
    dp = max(1, n // mp)
    mesh = ProcessMesh(np.arange(dp * mp).reshape(dp, mp), ["dp", "mp"])
    cfg = dict(config or {})
    cfg.setdefault("dp_config", {"sharding_level": 1})
    out = parallelize(model, optimizer, mesh, cfg)
    if optimizer is None:
        return out, None, dataloader
    model, optimizer = out
    return model, optimizer, dataloader
