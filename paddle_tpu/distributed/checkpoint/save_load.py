"""Distributed sharded checkpoint (reference:
distributed/checkpoint/save_state_dict.py:135, load_state_dict.py:526,
metadata.py — per-rank shard files + a global metadata index, dedup of replicated
shards, reshard-on-load across different meshes/placements).

TPU-native: each host process writes the shards it owns (addressable shards of the
sharded jax.Array), keyed by global offset; the metadata JSON maps tensor -> shard
files+offsets. Load reassembles the global value (reading only needed shards) and
re-places it under the *target* tensor's sharding — reshard-on-load for free.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ..env import global_rank, get_world_size


class Metadata(dict):
    pass


class LoadMetadata(dict):
    pass


def _tensor_items(state_dict, prefix=""):
    for k, v in state_dict.items():
        name = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            yield from _tensor_items(v, name)
        elif isinstance(v, Tensor):
            yield name, v
        elif isinstance(v, (jax.Array, np.ndarray)):
            yield name, Tensor(jnp.asarray(v))
        else:
            yield name, v


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    rank = global_rank()
    meta = {"tensors": {}, "nonb": {}, "world_size": get_world_size()}
    shard_file = os.path.join(path, f"{rank}_0.distcp")
    shards_out = {}
    for name, t in _tensor_items(state_dict):
        if not isinstance(t, Tensor):
            # only JSON-native scalars survive the metadata roundtrip;
            # numpy scalars coerce via item(), anything else is skipped
            # (json default=str would corrupt it into a string on load)
            if isinstance(t, (np.integer, np.floating, np.bool_)):
                meta["nonb"][name] = t.item()
            elif isinstance(t, (int, float, bool, str, type(None))):
                meta["nonb"][name] = t
            continue
        v = t._value
        entry = {"shape": list(v.shape), "dtype": str(np.dtype(v.dtype)),
                 "shards": []}
        seen_offsets = set()
        if isinstance(v, jax.Array) and v.sharding is not None \
                and len(v.addressable_shards) > 0:
            for s in v.addressable_shards:
                idx = s.index
                offset = tuple(sl.start or 0 for sl in idx)
                lengths = tuple((sl.stop if sl.stop is not None else dim) -
                                (sl.start or 0)
                                for sl, dim in zip(idx, v.shape)) if idx else \
                    tuple(v.shape)
                if offset in seen_offsets:
                    continue  # dedup replicated shards (reference dedup pass)
                seen_offsets.add(offset)
                skey = f"{name}@{offset}"
                shards_out[skey] = np.asarray(s.data)
                entry["shards"].append({"offset": list(offset),
                                        "lengths": list(lengths),
                                        "file": os.path.basename(shard_file),
                                        "key": skey})
        else:
            skey = f"{name}@full"
            shards_out[skey] = np.asarray(v)
            entry["shards"].append({"offset": [0] * v.ndim,
                                    "lengths": list(v.shape),
                                    "file": os.path.basename(shard_file),
                                    "key": skey})
        meta["tensors"][name] = entry
    with open(shard_file, "wb") as f:  # file handle: keep the .distcp name verbatim
        np.savez(f, **shards_out)
    # every rank writes its own piece of metadata; rank0's file carries the merge
    if get_world_size() > 1:
        from ..collective import all_gather_object
        metas = []
        all_gather_object(metas, meta)
        if rank == coordinator_rank:
            merged = {"tensors": {}, "nonb": {}}
            for m in metas:
                merged["nonb"].update(m["nonb"])
                for name, entry in m["tensors"].items():
                    tgt = merged["tensors"].setdefault(
                        name, {"shape": entry["shape"], "dtype": entry["dtype"],
                               "shards": []})
                    have = {tuple(s["offset"]) for s in tgt["shards"]}
                    for s in entry["shards"]:
                        if tuple(s["offset"]) not in have:
                            tgt["shards"].append(s)
            meta = merged
    if rank == coordinator_rank:
        with open(os.path.join(path, "0.metadata"), "w") as f:
            json.dump(meta, f, default=str)


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, offload=False):
    """Fill `state_dict`'s tensors in place; placements of the *targets* decide the
    final sharding (reshard-on-load)."""
    with open(os.path.join(path, "0.metadata")) as f:
        meta = json.load(f)
    cache = {}

    def shard_data(fname, key):
        if fname not in cache:
            cache[fname] = np.load(os.path.join(path, fname), allow_pickle=False)
        return cache[fname][key]

    # restore non-tensor entries (step counters, scheduler scalars): loss
    # continuity across a mesh reshape needs e.g. AdamW's bias-correction
    # step to survive the reload, not just the slot arrays
    def _restore_nonb(d, prefix=""):
        for k in list(d.keys()):
            name = f"{prefix}.{k}" if prefix else str(k)
            v = d[k]
            if isinstance(v, dict):
                _restore_nonb(v, name)
            elif not isinstance(v, (Tensor, jax.Array, np.ndarray)) \
                    and name in meta.get("nonb", {}):
                restored = meta["nonb"][name]
                # checkpointed nonb entries are JSON-native by construction
                # (save coerces numpy scalars, skips the rest); keep the
                # target's python type when it has one
                if v is not None and type(v) in (int, float, bool, str):
                    restored = type(v)(restored)
                d[k] = restored

    _restore_nonb(state_dict)

    for name, t in _tensor_items(state_dict):
        if not isinstance(t, Tensor):
            continue
        entry = meta["tensors"].get(name)
        if entry is None:
            raise KeyError(f"checkpoint missing tensor {name}")
        full = np.zeros(entry["shape"], np.dtype(entry["dtype"]))
        for s in entry["shards"]:
            sl = tuple(slice(o, o + l) for o, l in zip(s["offset"], s["lengths"]))
            full[sl] = shard_data(s["file"], s["key"])
        target_sharding = None
        if isinstance(t._value, jax.Array):
            try:
                target_sharding = t._value.sharding
            except Exception:
                target_sharding = None
        arr = jnp.asarray(full, dtype=t._value.dtype)
        from jax.sharding import NamedSharding
        if isinstance(target_sharding, NamedSharding):
            # reshard-on-load: re-place under the target's mesh placement.
            # Single-device targets stay UNCOMMITTED — committing them to
            # one device would pin later jits off the mesh.
            arr = jax.device_put(arr, target_sharding)
        t._value = arr
    return state_dict
