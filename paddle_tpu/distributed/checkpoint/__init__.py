from .save_load import (  # noqa: F401
    save_state_dict, load_state_dict, LoadMetadata, Metadata,
)
