"""paddle.distributed analog — TPU-native distributed stack.

Map (SURVEY §5.8, §2.7): rendezvous = TCPStore + jax.distributed.initialize;
device collectives = compiled XLA ops over ICI/DCN; DistTensor = mesh-placed
jax.Array + DistMeta; fleet = hybrid-parallel orchestration (TP/PP/ZeRO/SP/EP)
over GSPMD + shard_map.
"""
from .mesh import (  # noqa: F401
    ProcessMesh, Placement, Shard, Replicate, Partial,
)
from .api import (  # noqa: F401
    shard_tensor, reshard, shard_layer, shard_optimizer, dtensor_from_local,
    dtensor_to_local, is_dist_tensor, full_value, logical_shape, DistMeta,
    ShardingStage1, ShardingStage2, ShardingStage3, split,
)
from .auto_parallel_static import (  # noqa: F401
    Strategy, DistModel, to_static, LocalLayer, shard_dataloader, shard_scaler,
    dtensor_from_fn, unshard_dtensor, set_mesh, get_mesh, DistAttr,
    ShardDataloader,
)
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, is_initialized, new_group,
    get_group, barrier, Group, get_backend, destroy_process_group,
)
from .collective import (  # noqa: F401
    ReduceOp, all_reduce, all_gather, all_gather_object, broadcast,
    broadcast_object_list, reduce, reduce_scatter, all_to_all, scatter, send, recv,
    isend, irecv, P2POp, batch_isend_irecv, functional, alltoall,
    alltoall_single, gather, scatter_object_list, wait,
)
from .parallel_env import (  # noqa: F401
    ParallelEnv, ParallelMode, ReduceType, is_available,
    gloo_init_parallel_env, gloo_barrier, gloo_release,
)
from .entry_attr import (  # noqa: F401
    ProbabilityEntry, CountFilterEntry, ShowClickEntry,
)
from .fleet_dataset import InMemoryDataset, QueueDataset  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
from . import io  # noqa: F401
from .store import TCPStore, create_or_get_global_tcp_store  # noqa: F401
from .parallel import DataParallel, shard_local_batch  # noqa: F401
from . import fleet  # noqa: F401
from .fleet.base_api import (  # noqa: F401
    Fleet, UtilBase, Role, UserDefinedRoleMaker, PaddleCloudRoleMaker,
    MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from . import checkpoint  # noqa: F401
from .auto_parallel_intermediate import (  # noqa: F401
    parallelize, ColWiseParallel, RowWiseParallel, SequenceParallelBegin,
    SequenceParallelEnd, SequenceParallelEnable, SequenceParallelDisable,
    PrepareLayerInput, PrepareLayerOutput, SplitPoint, to_distributed,
)
from .sharding import group_sharded_parallel  # noqa: F401
from .launch_utils import spawn  # noqa: F401
from .watchdog import Watchdog, ErrorHandlingMode  # noqa: F401
from .auto_tuner import AutoTuner  # noqa: F401
from . import launch  # noqa: F401
from . import rpc  # noqa: F401
