"""File-backed training datasets for the PS/fleet path: InMemoryDataset /
QueueDataset.

Reference: python/paddle/distributed/fleet/dataset/dataset.py — C++ data_feed
readers (fluid/framework/data_feed.cc) that parse slot-formatted text files
into batches, with in-memory global/local shuffle (InMemoryDataset) or
streaming queues (QueueDataset). TPU-native: host-side Python readers feeding
numpy batches (device transfer happens in the training step); the slot text
format is `slot_id:v1 v2 ...` per field, whitespace-separated floats by
default, overridable with parse_fn.
"""
from __future__ import annotations

import random


def _default_parse(line):
    """'v1 v2;v3 v4' → one list per ';'-separated slot, floats."""
    parts = line.strip().split(";")
    out = []
    for p in parts:
        toks = p.split()
        try:
            out.append([float(t) for t in toks])
        except ValueError:
            out.append(toks)
    return out


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._use_var = []
        self._filelist = []
        self._pipe_command = None
        self._parse_fn = _default_parse
        self._input_type = 0

    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command=None,
             input_type=0, fs_name="", fs_ugi="", download_cmd="cat",
             parse_fn=None, **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        self._use_var = list(use_var or [])
        self._pipe_command = pipe_command
        self._input_type = input_type
        if parse_fn is not None:
            self._parse_fn = parse_fn

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _update_settings(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, "_" + k, v)

    def _read_records(self, files):
        import subprocess
        for path in files:
            if self._pipe_command:
                proc = subprocess.run(
                    self._pipe_command, shell=True, stdin=open(path, "rb"),
                    capture_output=True, check=False)
                lines = proc.stdout.decode().splitlines()
            else:
                with open(path) as f:
                    lines = f.read().splitlines()
            for line in lines:
                if line.strip():
                    yield self._parse_fn(line)

    def _batches(self, records):
        batch = []
        for r in records:
            batch.append(r)
            if len(batch) == self._batch_size:
                yield self._collate(batch)
                batch = []
        if batch:
            yield self._collate(batch)

    def _collate(self, rows):
        import numpy as np
        n_slots = max(len(r) for r in rows)
        out = []
        for s in range(n_slots):
            vals = [r[s] if s < len(r) else [] for r in rows]
            if any(isinstance(t, str) for v in vals for t in v):
                # string slots (e.g. id features) batch as ragged lists —
                # the reference feeds these to string slots of the PS tables
                out.append(vals)
                continue
            w = max(len(v) for v in vals)
            arr = np.zeros((len(rows), w), np.float32)
            for i, v in enumerate(vals):
                arr[i, : len(v)] = v
            out.append(arr)
        return out


class QueueDataset(DatasetBase):
    """Streaming reader (reference: dataset.py QueueDataset — no shuffle, one
    pass over the filelist)."""

    def __iter__(self):
        yield from self._batches(self._read_records(self._filelist))


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle reader (reference: dataset.py InMemoryDataset —
    load_into_memory / local_shuffle / global_shuffle / release_memory)."""

    def __init__(self):
        super().__init__()
        self._memory = []

    def load_into_memory(self):
        self._memory = list(self._read_records(self._filelist))

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        random.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-controller: global == local
        random.shuffle(self._memory)

    def get_memory_data_size(self, fleet=None):
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._memory)

    def release_memory(self):
        self._memory = []

    def slots_shuffle(self, slots):
        idx = list(range(len(self._memory)))
        random.shuffle(idx)
        for s in slots:
            s = int(s)
            vals = [self._memory[i][s] if s < len(self._memory[i]) else None
                    for i in idx]
            for row, v in zip(self._memory, vals):
                if s < len(row) and v is not None:
                    row[s] = v

    def __iter__(self):
        yield from self._batches(iter(self._memory))
