"""Driver benchmark — one JSON line per BASELINE workload config.

Default (`BENCH_MODEL` unset / `all`): runs every BASELINE.md config plus
the decode and serving benchmarks — resnet50, bert, vit, unet, llama_decode
(plus its int8/int4 weight-only rungs, re-baselining the quantized decode
ratios every run), llama_paged_decode (Pallas paged-attention kernel
on/off A/B), llama_serve (flight-recorder, supervision AND multi-step
readout-stride on/off A/Bs — the latter reports per-arm
rtt/dispatch/host-sync shares), llama_serve_fused (fused prefill+decode
scheduler on/off A/B), llama_serve_prefix_cache (automatic prefix caching
on/off A/B: shared-system-prompt hit-rate + zero-reuse overhead guard),
llama_serve_slo (multi-tenant SLO isolation: adversarial flood vs victim
tenant, per-tenant p99 TTFT + burn-rate alert fire/clear),
llama_serve_spec, then the flagship llama LAST — each in its own
subprocess, one JSON line each, so the tail line stays the llama MFU vs
the 45% north star (BASELINE.json).
`BENCH_MODEL=llama` (or any single name) prints exactly one line.

The flagship line measures the fused compiled training step (fwd+bwd+AdamW,
bf16 params + fp32 master weights, Pallas flash attention) of a Llama-family
decoder on one TPU chip. Model size is chosen to fill a single v5e chip
(16 GB HBM); on a pod slice the same code scales via the fleet
hybrid-parallel path (see __graft_entry__.py).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

# peak dense bf16 FLOPs/s per chip by TPU generation
_PEAK = {
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12, "v4": 275e12,
    "v6 lite": 918e12, "v6e": 918e12, "v3": 123e12, "v2": 45e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK.items():
        if key in kind:
            return val
    return 197e12  # assume v5e


def _time_train_step(step, args, steps):
    """Differential timing of a TrainStep through the tunnel (one warmup
    cycle, subtract one timed unit, sync via scalar loss fetch)."""
    loss = step(*args)
    float(np.asarray(loss._value))
    t0 = time.perf_counter()
    loss = step(*args)
    float(np.asarray(loss._value))
    d1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(steps + 1):
        loss = step(*args)
    final_loss = float(np.asarray(loss._value))
    dn = time.perf_counter() - t0
    return max(dn - d1, 1e-9) / steps, final_loss


def _forward_flops(model, arg_tensors):
    """Model FLOPs of one forward pass from XLA's cost model on the
    UNOPTIMIZED lowered HLO — i.e. the math as written, so grad-checkpoint
    recompute does not inflate the number. Returns None when the jax version
    can't produce a cost analysis."""
    import jax
    from paddle_tpu.core.tensor import Tensor, functional_mode
    from paddle_tpu.jit.functional_call import collect_state, bind_state

    _, params, _, buffers = collect_state(model)
    state = params + buffers

    def fwd(state_vals, arg_vals):
        with functional_mode(), bind_state(state, state_vals):
            out = model(*[Tensor(v) for v in arg_vals])
        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: hasattr(x, "_value"))
        return [getattr(x, "_value", x) for x in leaves]

    try:
        lowered = jax.jit(fwd).lower([t._value for t in state],
                                     [t._value for t in arg_tensors])
        def norm(c):
            return c[0] if isinstance(c, (list, tuple)) else c

        cost = norm(lowered.cost_analysis())
        if cost is None or "flops" not in cost:
            # some backends (the axon TPU tunnel) only cost-analyze the
            # COMPILED module; forward-only, so remat can't inflate it
            cost = norm(lowered.compile().cost_analysis())
        return float(cost["flops"])
    except Exception:
        return None


def _artifact_dir():
    """Where serve benches persist their observability artifacts
    (telemetry snapshots, sample chrome traces): BENCH_ARTIFACT_DIR or
    docs/artifacts next to this file. Created on demand."""
    d = os.environ.get(
        "BENCH_ARTIFACT_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "docs", "artifacts"))
    os.makedirs(d, exist_ok=True)
    return d


def _serve_multi_step_ab(model, prompts, new_tokens, B, cap, stride,
                         rtt_s=0.0, chunk_size=256, pipeline_depth=2,
                         timeout=1800):
    """Multi-step on-device decode A/B: the same prompts served through
    TWO fused-scheduler engines — ``readout_stride=stride`` (the k-step
    compiled decode loop with in-graph early exit) vs ``stride=1`` (one
    host round-trip per decode step). Per arm, the host-tax split comes
    from the FLIGHT RECORDER's StepRecords (the engine-measured
    dispatch/sync wall splits, summed over the run):

    * ``host_sync_share``  — device→host token syncs / wall,
    * ``dispatch_share``   — host-side dispatch enqueue / wall,
    * ``rtt_share``        — rtt_s x host round-trips / wall (each
      StepRecord is one round-trip; the stride arm makes ~1/k as many),
    * ``host_tax_s`` / ``host_tax_ms_per_token`` — host_sync + dispatch
      in ABSOLUTE seconds (and per token). The arms serve the identical
      workload, so this is the fair cross-arm comparison everywhere: on
      CPU the dispatch timer absorbs blocked device compute (no real
      async enqueue), which inflates the FASTER arm's share-of-own-wall
      even as its absolute host tax drops; on TPU (true async dispatch)
      the share comparison agrees with the absolute one.

    Greedy streams must be token-exact across arms (asserted); the
    returned dict carries both arms plus ``multi_step_speedup``.
    Shared by the llama_serve bench and the tier-1 CPU smoke test."""
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.serving import AsyncLLMServer
    from paddle_tpu.profiler import FlightRecorder

    arms, streams = {}, {}
    for arm, s in (("off", 1), ("on", int(stride))):
        eng = LLMEngine(model, max_batch=B, max_seq_len=cap,
                        chunk_size=chunk_size, scheduler="fused",
                        readout_stride=s)
        eng.generate([prompts[0]], max_new_tokens=2)  # warm the programs
        eng.reset_stats()
        rec = FlightRecorder()
        srv = AsyncLLMServer(eng, max_queue_size=len(prompts) + 1,
                             flight_recorder=rec,
                             pipeline_depth=pipeline_depth)
        srv.start()
        t0 = time.perf_counter()
        hs = [srv.submit(p, max_new_tokens=new_tokens) for p in prompts]
        outs = [h.result(timeout=timeout) for h in hs]
        wall = time.perf_counter() - t0
        srv.stop()
        toks = sum(len(o.token_ids) for o in outs)
        recs = rec.records()
        sync_s = sum(r.sync_s for r in recs)
        disp_s = sum(r.dispatch_s for r in recs)
        arms[arm] = {
            "readout_stride": s,
            "tokens_per_sec": round(toks / wall, 1),
            "host_round_trips": len(recs),
            "multi_steps": int(eng.stats["multi_steps"]),
            "host_sync_share": round(sync_s / wall, 4),
            "dispatch_share": round(disp_s / wall, 4),
            "rtt_share": round(rtt_s * len(recs) / wall, 4),
            "host_tax_s": round(sync_s + disp_s, 4),
            "host_tax_ms_per_token": round(
                (sync_s + disp_s) / max(toks, 1) * 1e3, 4),
            "pipeline_depth": srv.pipeline_depth,
        }
        streams[arm] = [o.token_ids for o in outs]
    token_parity = streams["on"] == streams["off"]
    assert token_parity, "multi-step decode changed a greedy stream"
    return {
        "multi_step_speedup": round(
            arms["on"]["tokens_per_sec"]
            / max(arms["off"]["tokens_per_sec"], 1e-9), 3),
        "readout_stride": int(stride),
        "token_parity": token_parity,
        "on": arms["on"], "off": arms["off"],
    }


def _bench_other(model_name):
    """Secondary BASELINE workloads (ResNet-50 / BERT-base MLM / ViT-L /
    SD-UNet) — same JSON contract, per-domain throughput metric. The driver
    default stays the flagship Llama config."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit.api import TrainStep

    if os.environ.get("BENCH_PRNG"):
        # 'rbg' = XLA's rng-bit-generator: hardware-rate random bits vs
        # threefry's VPU integer chains — the lever for dropout-mask cost
        # on elementwise dropout sites (distribution-identical, different
        # stream)
        jax.config.update("jax_default_prng_impl",
                          os.environ["BENCH_PRNG"])
    steps = int(os.environ.get("BENCH_STEPS", "8"))
    rng = np.random.default_rng(0)
    paddle.seed(0)
    peak = _peak_flops(jax.devices()[0])

    if model_name == "resnet50":
        from paddle_tpu.vision.models import resnet50
        B = int(os.environ.get("BENCH_BATCH", "128"))
        # NHWC end-to-end: the TPU-preferred conv layout (~1.5x the 3x3
        # stack vs NCHW, no transposes anywhere); BENCH_LAYOUT=NCHW for A/Bs
        layout = os.environ.get("BENCH_LAYOUT", "NHWC")
        model = resnet50(num_classes=1000, data_format=layout).bfloat16()
        optimizer = opt.Momentum(learning_rate=0.1, momentum=0.9,
                                 parameters=model.parameters())
        step = TrainStep(model, lambda m, x, y: F.cross_entropy(m(x), y),
                         optimizer)
        shape = (B, 3, 224, 224) if layout == "NCHW" else (B, 224, 224, 3)
        x = paddle.to_tensor(rng.standard_normal(
            shape).astype(np.float32)).astype("bfloat16")
        y = paddle.to_tensor(rng.integers(0, 1000, B))
        # forward FLOPs from XLA's cost model (train = 3x fwd). The old
        # hand constant (3 * 4.1e9 * B) was GMACs, not FLOPs — it halved
        # the reported MFU; the per-instruction HLO count in
        # docs/artifacts/conv_roofline_proof.json confirms ~8.2 GFLOP/img
        fwd_flops = _forward_flops(model, (x,))
        dt, loss = _time_train_step(step, (x, y), steps)
        flops = 3 * (fwd_flops if fwd_flops is not None else 8.2e9 * B)
        return {"metric": "resnet50_1chip_train_imgs_per_sec",
                "value": round(B / dt, 1), "unit": "imgs/s",
                "vs_baseline": None, "mfu_pct": round(flops / dt / peak * 100, 2),
                "step_time_s": round(dt, 4), "loss": loss}

    if model_name == "bert":
        from paddle_tpu.models import BertConfig, BertForMaskedLM
        # Round-5 sweep (24-step runs), all at rbg dropout masks (+2.6 MFU
        # over threefry — hardware rng-bit-generator vs VPU integer
        # chains): 48/42.0 STABLE, 64/37.0 (spilling schedule), 96/~52
        # WHEN it compiles — the no-remat B=96 program OOMs
        # nondeterministically under remote-compiler fusion variance, so
        # the bench LADDERS 96 -> 48 -> 24. The alternatives were
        # measured and rejected: full remat costs exactly the +1/3
        # recompute FLOPs on this compute-bound model (50.7 -> 38.0
        # dropout-free), dots_saveable remat still OOMs at 96 (keeps the
        # dot outputs) and only adds cost at 48 (30.9), and the chunked
        # fused head compiles B=96 DETERMINISTICALLY but its +23% head
        # FLOPs land at a stable 34.8 — worse than the 48-rung
        # (BENCH_CHUNKED_HEAD=1 to opt in; it remains the right tool for
        # larger-vocab models).
        if "BENCH_PRNG" not in os.environ:
            jax.config.update("jax_default_prng_impl", "rbg")
        B = int(os.environ.get("BENCH_BATCH", "96"))
        S = int(os.environ.get("BENCH_SEQ", "512"))
        cfg = BertConfig(
            max_position_embeddings=S,
            hidden_dropout_prob=float(os.environ.get("BENCH_DROPOUT", "0.1")),
            attention_probs_dropout_prob=float(
                os.environ.get("BENCH_ATTN_DROPOUT", "0.1")),
            # SELECTIVE remat: bert is compute-bound, so full remat costs
            # the whole +1/3 step FLOPs (measured 50.7 -> 38.0% MFU); a few
            # rematted layers shave just the compile-time temp peak that
            # made no-remat B=96 OOM nondeterministically
            use_recompute=os.environ.get("BENCH_REMAT", "0") == "1",
            recompute_layers=int(os.environ.get("BENCH_REMAT_LAYERS", "12")),
            recompute_policy=os.environ.get("BENCH_REMAT_POLICY") or None,
            fuse_mlm_head_ce=os.environ.get("BENCH_CHUNKED_HEAD",
                                            "0") == "1")
        if os.environ.get("BENCH_BF16_MOMENTS", "1") == "1":
            # same lever as the vit config: AdamW moment traffic in bf16
            from paddle_tpu.core.flags import set_flags
            set_flags({"adamw_bf16_moments": True})
        # rung choice is measured: 96/50.5 (when it compiles), 48/39.8-40.2,
        # 24/38.4 — and 64 is a trap (31.4%: the compiler picks a spilling
        # schedule there), so the ladder skips it
        ladder = [b for b in (B, 48, 24) if b <= B] or [B]
        last_err = None
        for B_try in ladder:
            paddle.seed(0)
            model = BertForMaskedLM(cfg).bfloat16()
            n_params = sum(int(np.prod(p.shape))
                           for p in model.parameters())
            optimizer = opt.AdamW(learning_rate=1e-4,
                                  parameters=model.parameters(),
                                  multi_precision=True)
            step = TrainStep(model,
                             lambda m, ids, lbl: m(ids, labels=lbl)[0],
                             optimizer)
            ids = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (B_try, S)), dtype="int32")
            lbl = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (B_try, S)), dtype="int32")
            try:
                dt, loss = _time_train_step(step, (ids, lbl), steps)
            except Exception as e:  # compile OOM at the edge config
                # only resource exhaustion ladders down — a genuine
                # regression (shape bug, import error) must fail loudly,
                # not silently demote the benchmark
                msg = str(e)
                if not any(t in msg.upper() for t in
                           ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                            "OUT OF MEMORY", "OOM", "ALLOCAT")):
                    raise
                # keep only the message — the exception's traceback would
                # pin this rung's device buffers and OOM every later rung
                last_err = RuntimeError(f"bert B={B_try}: {msg[:300]}")
                del step, optimizer, model, ids, lbl
                import gc
                gc.collect()
                continue
            toks = B_try * S / dt
            mfu = 6 * n_params * toks / peak
            return {"metric": "bert_base_mlm_1chip_tokens_per_sec",
                    "value": round(toks, 1), "unit": "tokens/s",
                    "vs_baseline": None, "mfu_pct": round(mfu * 100, 2),
                    "step_time_s": round(dt, 4), "params": n_params,
                    "batch": B_try,
                    "prng": os.environ.get("BENCH_PRNG", "rbg"),
                    "loss": loss}
        raise last_err

    if model_name == "vit":
        from paddle_tpu.vision.models import vit_large_patch16
        # defaults = best measured config (round 4 sweep, 24-step runs):
        # B=40 + bf16 AdamW moments -> 45.4% MFU (was 38.0 at B=32 + fp32
        # moments). The gap was optimizer-state traffic (307M params x 8B
        # fp32 moments r/w per step) plus too little per-step compute to
        # amortize the weight+state streaming; B>=56 regresses again
        # (activation working set without remat). Curve: 32/38.0, 32+bf16m/
        # 39.0, 40/45.4, 48/44.1-44.5, 56/42.5, 64/43.1, 72/40.3, 96/36.5.
        B = int(os.environ.get("BENCH_BATCH", "40"))
        if os.environ.get("BENCH_BF16_MOMENTS", "1") == "1":
            from paddle_tpu.core.flags import set_flags
            set_flags({"adamw_bf16_moments": True})
        model = vit_large_patch16(num_classes=1000).bfloat16()
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        optimizer = opt.AdamW(learning_rate=3e-4,
                              parameters=model.parameters(),
                              multi_precision=True)
        step = TrainStep(model, lambda m, x, y: F.cross_entropy(m(x), y),
                         optimizer)
        x = paddle.to_tensor(rng.standard_normal(
            (B, 3, 224, 224)).astype(np.float32)).astype("bfloat16")
        y = paddle.to_tensor(rng.integers(0, 1000, B))
        dt, loss = _time_train_step(step, (x, y), steps)
        tokens_per_img = (224 // 16) ** 2 + 1
        mfu = 6 * n_params * tokens_per_img * B / dt / peak
        return {"metric": "vit_large_1chip_train_imgs_per_sec",
                "value": round(B / dt, 1), "unit": "imgs/s",
                "vs_baseline": None, "mfu_pct": round(mfu * 100, 2),
                "step_time_s": round(dt, 4), "params": n_params, "loss": loss}

    if model_name == "unet":
        from paddle_tpu.models import (UNetConfig, UNetModel, diffusion_loss)
        import jax.numpy as jnp
        B = int(os.environ.get("BENCH_BATCH", "4"))
        cfg = UNetConfig.sd_unet(
            use_recompute=os.environ.get("BENCH_REMAT", "1") == "1")
        model = UNetModel(cfg).bfloat16()
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        optimizer = opt.AdamW(learning_rate=1e-4,
                              parameters=model.parameters(),
                              multi_precision=True)
        alphas = paddle.to_tensor(np.linspace(0.999, 0.01, 1000)
                                  .astype(np.float32))

        def loss_fn(m, lat, t, ctx, noise):
            return diffusion_loss(m, lat, t, ctx, noise, alphas)

        step = TrainStep(model, loss_fn, optimizer)
        # NHWC: the TPU-native UNet is channels-last throughout (models/unet.py)
        lat = paddle.to_tensor(rng.standard_normal(
            (B, 64, 64, 4)).astype(np.float32)).astype("bfloat16")
        t = paddle.to_tensor(rng.integers(0, 1000, B))
        ctx = paddle.to_tensor(rng.standard_normal(
            (B, 77, 768)).astype(np.float32)).astype("bfloat16")
        noise = paddle.to_tensor(rng.standard_normal(
            (B, 64, 64, 4)).astype(np.float32)).astype("bfloat16")
        # forward FLOPs via XLA's cost model (train = 3x fwd); measured BEFORE
        # the timed steps so its trace never lands in a timing window
        fwd_flops = _forward_flops(model, (lat, t, ctx))
        dt, loss = _time_train_step(step, (lat, t, ctx, noise), steps)
        out = {"metric": "sd_unet_1chip_train_samples_per_sec",
               "value": round(B / dt, 2), "unit": "samples/s",
               "vs_baseline": None, "step_time_s": round(dt, 4),
               "params": n_params, "loss": loss}
        if fwd_flops is not None:
            out["mfu_pct"] = round(3 * fwd_flops / dt / peak * 100, 2)
        return out

    if model_name == "llama_decode":
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.jit.functional_call import collect_state, read_values
        import jax.numpy as jnp
        B = int(os.environ.get("BENCH_BATCH", "8"))
        prompt = int(os.environ.get("BENCH_PROMPT", "512"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "128"))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=prompt + new_tokens)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        # logical param count, BEFORE any quantized re-packing
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        # weight-only quantized decode (BENCH_WEIGHT_DTYPE=int8|int4):
        # decode is weight-bandwidth-bound, so halving/quartering the
        # weight bytes per token-step is the serving-throughput lever
        weight_dtype = os.environ.get("BENCH_WEIGHT_DTYPE", "")
        if weight_dtype:
            from paddle_tpu.nn.quant import quantize_linears_for_inference
            quantize_linears_for_inference(model, weight_dtype=weight_dtype)
        ids_v = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, prompt)),
                            jnp.int32)
        # TWO-LENGTH DIFFERENTIAL (VERDICT r4 #7): time the full
        # prefill+decode pair at new_tokens and at a short control length,
        # and divide the time DELTA by the token delta. The old
        # pair-minus-prefill method subtracted a separately-timed prefill,
        # which under-subtracts fixed per-call costs (dispatch, donation
        # relayout, tunnel RTT) and INFLATES absolute decode tok/s — the
        # builder's own int4 A/B already used this honest form.
        short = min(max(new_tokens // 8, 8), max(new_tokens // 2, 1))
        _, params, _, buffers = collect_state(model)
        state_vals = read_values(params + buffers)
        key = jax.random.PRNGKey(0)
        total = prompt + new_tokens

        def build_pair(n_new):
            prefill, decode = model._gen_programs(
                B, prompt, n_new, prompt + n_new, 0.0, 0, 1.0, None,
                "static", 64)

            def run_pair():
                l0, kb, vb = prefill(state_vals, ids_v)
                buf, n = decode(state_vals, kb, vb, l0, key,
                                jnp.float32(1.0), jnp.float32(1.0))
                int(np.asarray(n))
                return buf
            return prefill, run_pair

        prefill, run_long = build_pair(new_tokens)
        _, run_short = build_pair(short)

        def run_prefill():
            l0, kb, vb = prefill(state_vals, ids_v)
            float(np.asarray(l0[0, 0]))  # tunnel-safe sync

        # warm every program twice (donated-output relayout recompiles must
        # not land in a timing window)
        for f in (run_long, run_short):
            f()
            f()
        run_prefill()
        reps = int(os.environ.get("BENCH_STEPS", "8"))
        t0 = time.perf_counter()
        for _ in range(reps):
            run_prefill()
        t_prefill = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            run_short()
        t_short = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            run_long()
        t_long = (time.perf_counter() - t0) / reps
        t_decode = max(t_long - t_short, 1e-9)
        n_delta = new_tokens - short
        return {"metric": "llama_decode_tokens_per_sec",
                "value": round(B * n_delta / t_decode, 1),
                "unit": "tokens/s", "vs_baseline": None,
                "method": "two-length-differential",
                "decode_ms_per_token": round(
                    t_decode / n_delta * 1e3, 3),
                "new_tokens_long_short": [new_tokens, short],
                "prefill_tokens_per_sec": round(B * prompt / t_prefill, 1),
                "prefill_s": round(t_prefill, 4),
                "batch": B, "prompt_len": prompt, "new_tokens": new_tokens,
                "weight_dtype": weight_dtype or "bf16",
                "params": n_params}

    if model_name == "llama_paged_decode":
        # Paged-KV decode throughput with the Pallas paged-attention kernel
        # A/B'd against the dense-gather XLA fallback
        # (FLAGS_use_paged_attention) — the recorded number behind the
        # block-sparse-read claim. Two-length differential like
        # llama_decode; GQA by default (kv_heads = heads/4) since the
        # kernel is what unlocks cache_impl="paged" for GQA models.
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.jit.functional_call import collect_state, read_values
        from paddle_tpu.core.flags import set_flags
        import jax.numpy as jnp
        B = int(os.environ.get("BENCH_BATCH", "8"))
        prompt = int(os.environ.get("BENCH_PROMPT", "512"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "128"))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        kv_heads = int(os.environ.get("BENCH_KV_HEADS",
                                      str(max(heads // 4, 1))))
        block_size = int(os.environ.get("BENCH_BLOCK_SIZE", "64"))
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=kv_heads,
                          max_position_embeddings=prompt + new_tokens)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        ids_v = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, prompt)),
                            jnp.int32)
        short = min(max(new_tokens // 8, 8), max(new_tokens // 2, 1))
        _, params, _, buffers = collect_state(model)
        state_vals = read_values(params + buffers)
        key = jax.random.PRNGKey(0)
        reps = int(os.environ.get("BENCH_STEPS", "8"))

        def run_arm(kernel_on):
            # flag is read at trace time: flip it, then force a fresh trace
            # of the paged decode programs for this arm
            set_flags({"use_paged_attention": bool(kernel_on)})
            model._gen_cache = {}

            def build_pair(n_new):
                prefill, decode = model._gen_programs(
                    B, prompt, n_new, prompt + n_new, 0.0, 0, 1.0, None,
                    "paged", block_size)

                def run_pair():
                    l0, kb, vb = prefill(state_vals, ids_v)
                    buf, n = decode(state_vals, kb, vb, l0, key,
                                    jnp.float32(1.0), jnp.float32(1.0))
                    int(np.asarray(n))
                return run_pair

            run_long = build_pair(new_tokens)
            run_short = build_pair(short)
            for f in (run_long, run_short):  # warm twice (donation relayout)
                f()
                f()
            t0 = time.perf_counter()
            for _ in range(reps):
                run_short()
            t_short = (time.perf_counter() - t0) / reps
            t0 = time.perf_counter()
            for _ in range(reps):
                run_long()
            t_long = (time.perf_counter() - t0) / reps
            t_decode = max(t_long - t_short, 1e-9)
            return B * (new_tokens - short) / t_decode

        on_cpu = jax.default_backend() == "cpu"
        try:
            toks_on = run_arm(True)     # Pallas block-sparse kernel
            # on CPU both arms would trace the identical dense fallback
            # (the kernel is TPU-gated) — skip the redundant off arm
            toks_off = toks_on if on_cpu else run_arm(False)
        finally:
            set_flags({"use_paged_attention": True})
        return {"metric": "llama_paged_decode_tokens_per_sec",
                "value": round(toks_on, 1), "unit": "tokens/s",
                "vs_baseline": None, "method": "two-length-differential",
                "kernel_on_tokens_per_sec": round(toks_on, 1),
                "kernel_off_tokens_per_sec": round(toks_off, 1),
                # on CPU both arms run the dense fallback (the kernel is
                # TPU-gated) — the A/B is only meaningful on-chip
                "kernel_speedup": (round(toks_on / toks_off, 2)
                                   if not on_cpu else None),
                "decode_ms_per_token": round(
                    B * 1e3 / max(toks_on, 1e-9), 3),
                "new_tokens_long_short": [new_tokens, short],
                "batch": B, "prompt_len": prompt, "new_tokens": new_tokens,
                "block_size": block_size, "q_heads": heads,
                "kv_heads": kv_heads, "params": n_params}

    if model_name == "llama_serve_spec":
        # Batched speculative decoding THROUGH THE FUSED SCHEDULER
        # (ROADMAP item 2): verify-k grants ride the same token-budget
        # walk as prefill chunks and plain decode tokens, so speculation
        # now serves at FULL BATCH instead of the legacy batch-1 latency
        # demo (r05's 46.8 tok/s line — a different serving path, so
        # vs_baseline stays null). Main arm: B=8 spec on/off A/B
        # (speculation_speedup at batch, per-arm acceptance rate +
        # rtt_share); plus the classic batch-1 latency arm (the regime
        # where accepted drafts are nearly free because a k+1-row verify
        # window streams the same weights as a 1-token step).
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_req = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        cap = 512 + new_tokens
        spec_k = int(os.environ.get("BENCH_SPEC_K", "6"))
        stride = int(os.environ.get("BENCH_READOUT_STRIDE", "4"))
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        # repetition-heavy prompts: the workload where prompt-lookup
        # drafts actually accept (greedy continuations loop)
        prompts = []
        for i in range(n_req):
            base = rng.integers(0, cfg.vocab_size, (24,)).astype(np.int32)
            want = 256 + int(rng.integers(0, 128))
            reps = -(-want // len(base))  # tile past the target length
            prompts.append(np.tile(base, reps)[:want])

        rtt = None

        def serve_arm(k, batch, reqs):
            """One serve pass through a fused-scheduler engine at
            speculative_k=k; k=1 is the A/B control (bit-identical to
            the plain fused engine by construction)."""
            nonlocal rtt
            eng = LLMEngine(model, max_batch=batch, max_seq_len=cap,
                            chunk_size=256, scheduler="fused",
                            speculative_k=k, readout_stride=stride)
            eng.generate([prompts[0]], max_new_tokens=2)  # warm programs
            if rtt is None:
                rtts = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    float(np.asarray(eng._logits[0, 0]))
                    rtts.append(time.perf_counter() - t0)
                rtt = sorted(rtts)[len(rtts) // 2]
            eng.reset_stats()
            srv = AsyncLLMServer(eng, max_queue_size=reqs + 1)
            srv.start()
            t0 = time.perf_counter()
            hs = [srv.submit(p, max_new_tokens=new_tokens)
                  for p in prompts[:reqs]]
            outs = [h.result(timeout=1800) for h in hs]
            wall = time.perf_counter() - t0
            srv.stop()
            toks = sum(len(o.token_ids) for o in outs)
            steps = eng.stats["steps"]
            prop = eng.stats["spec_proposed_tokens"]
            acc = eng.stats["spec_accepted_tokens"]
            return {"tokens_per_sec": round(toks / wall, 1),
                    "batch": batch, "speculative_k": k,
                    "requests": reqs, "steps": steps,
                    "acceptance_rate": (round(acc / prop, 4)
                                        if prop else None),
                    "accepted_per_step": round(
                        eng.stats["draft_tokens_accepted"]
                        / max(steps, 1), 2),
                    # per-arm host-RTT share: speculation's win is
                    # FEWER host passes per token — this is the split
                    # that should drop on the spec arm
                    "rtt_share": round(rtt * steps / wall, 4),
                    "_outputs": [o.token_ids for o in outs]}

        b8_on = serve_arm(spec_k, B, n_req)
        b8_off = serve_arm(1, B, n_req)
        # greedy token parity across the A/B: speculation must never
        # change a stream (the coupled acceptance rule's contract)
        parity = b8_on.pop("_outputs") == b8_off.pop("_outputs")
        b1_n = min(3, n_req)
        b1_on = serve_arm(spec_k, 1, b1_n)
        b1_off = serve_arm(1, 1, b1_n)
        parity_b1 = b1_on.pop("_outputs") == b1_off.pop("_outputs")
        return {
            "metric": "llama_serve_spec_tokens_per_sec",
            "value": b8_on["tokens_per_sec"], "unit": "tokens/s",
            # r05's 46.8 was the legacy batch-1 latency demo — a
            # different serving path; the batched fused line has no
            # captured baseline to ratio against
            "vs_baseline": None,
            "scheduler": "fused", "readout_stride": stride,
            "speculative_k": spec_k, "slots": B,
            "new_tokens": new_tokens,
            "prompt_lens": f"{min(len(p) for p in prompts)}-"
                           f"{max(len(p) for p in prompts)}",
            "speculation_speedup": round(
                b8_on["tokens_per_sec"]
                / max(b8_off["tokens_per_sec"], 1e-9), 3),
            "speculation_speedup_b1": round(
                b1_on["tokens_per_sec"]
                / max(b1_off["tokens_per_sec"], 1e-9), 3),
            "token_parity": bool(parity and parity_b1),
            "spec_on": b8_on, "spec_off": b8_off,
            "latency_b1": {"spec_on": b1_on, "spec_off": b1_off},
            "rtt_est_ms": round(rtt * 1e3, 1),
            # r05 trend anchor: the LEGACY spec path's rtt share (0.324)
            # — the batched fused arm's rtt_share above is the number
            # that should sit far below it
            "rtt_share_r05_legacy": 0.324}

    if model_name == "llama_serve":
        # ASYNC serving subsystem (paddle_tpu/serving/ over
        # inference/llm_engine.py): mixed-length requests through fixed
        # slots, chunked prefill, per-step host transfer = one [B] token
        # vector — now driven by AsyncLLMServer's PIPELINED loop (step
        # N+1 dispatched before step N's token sync, so the tunnel RTT of
        # the transfer overlaps the next step's device compute) with
        # per-stage telemetry attributing the serve wall (VERDICT r5 #4:
        # the old sync loop left ~76% of wall unexplained).
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_req = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        cap = 512 + new_tokens
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        weight_dtype = os.environ.get("BENCH_WEIGHT_DTYPE", "")
        if weight_dtype:
            from paddle_tpu.nn.quant import quantize_linears_for_inference
            quantize_linears_for_inference(model, weight_dtype=weight_dtype)
        # horizon 64 ~= one step per request generation (new_tokens=64):
        # through the tunnel each step() costs one RTT, so tokens/s scales
        # ~linearly in horizon up to the point admissions coarsen
        horizon = int(os.environ.get("BENCH_HORIZON", "64"))
        eng = LLMEngine(model, max_batch=B, max_seq_len=cap, chunk_size=256,
                        horizon=horizon)
        lens = [256 + int(x) for x in
                rng.integers(0, 256, size=n_req)]  # mixed prompts
        prompts = [rng.integers(0, cfg.vocab_size, (L,)).astype(np.int32)
                   for L in lens]
        # warm the programs (prefill + step) outside the timed window
        eng.generate([prompts[0]], max_new_tokens=2)
        # tunnel RTT estimate: a scalar fetch of resident device data
        # (VERDICT r4 #5). Under the pipelined loop the RTT of the token
        # transfer overlaps step N+1's compute, so this is reported as
        # context, not as an exclusive wall share.
        rtts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(np.asarray(eng._logits[0, 0]))
            rtts.append(time.perf_counter() - t0)
        rtt = sorted(rtts)[len(rtts) // 2]
        eng.reset_stats()
        server = AsyncLLMServer(eng, max_queue_size=n_req + 1)
        server.start()
        t0 = time.perf_counter()
        handles = [server.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        outs = [h.result(timeout=1800) for h in handles]
        wall = time.perf_counter() - t0
        server.stop()
        toks = sum(len(o.token_ids) for o in outs)
        steps = eng.stats["steps"]
        stats_off = dict(eng.stats)  # the A/B below keeps stepping eng
        snap = server.telemetry.snapshot(wall_s=wall)
        att = snap["attribution"]
        lat = snap["latency"]

        # flight-recorder A/B: the same prompts re-served with the
        # recorder ON (per-step StepRecords + per-request timelines).
        # Budget: <2% tok/s regression — the ring append + token stamps
        # must stay invisible next to device decode. A single sequential
        # pair would drown the 2% budget in serve-wall noise (ROUND4:
        # ±20% run-to-run on this metric), so the arms ALTERNATE
        # on/off/on/off/on/off and each side takes its median-of-3. The
        # recorded arm's telemetry snapshot and a sample chrome trace
        # persist next to the bench output so a slow-token question
        # ("why was THIS token slow?") can be answered from the
        # artifact, not a re-run.
        from paddle_tpu.profiler import FlightRecorder

        def serve_pass(rec, supervise=None, step_timeout_s=None,
                       metrics_store=None, trace_context=True):
            srv = AsyncLLMServer(eng, max_queue_size=n_req + 1,
                                 flight_recorder=rec, supervise=supervise,
                                 step_timeout_s=step_timeout_s,
                                 metrics_store=metrics_store,
                                 trace_context=trace_context)
            srv.start()
            t0 = time.perf_counter()
            hs = [srv.submit(p, max_new_tokens=new_tokens)
                  for p in prompts]
            outs = [h.result(timeout=1800) for h in hs]
            w = time.perf_counter() - t0
            srv.stop()
            return sum(len(o.token_ids) for o in outs) / w, srv, w

        on_tps, off_tps = [], [toks / wall]
        for _ in range(3):
            recorder = FlightRecorder()
            tps, server_on, wall_on = serve_pass(recorder)
            on_tps.append(tps)
            if len(off_tps) < 3:
                off_tps.append(serve_pass(None)[0])

        def median(xs):
            return sorted(xs)[len(xs) // 2]

        tps_off, tps_on = median(off_tps), median(on_tps)
        rec_overhead_pct = round((tps_off - tps_on) / tps_off * 100, 2)

        # supervision A/B (fault-tolerance satellite): the same prompts
        # re-served under supervise=RestartPolicy() with the watchdog
        # armed. Budget: <1% tok/s — the per-pass cost supervision adds
        # to the serve loop is ONE monotonic heartbeat read (the
        # watchdog is a separate mostly-sleeping thread, and the
        # restart machinery runs only on a crash). Supervision-OFF
        # overhead is 0 BY CONSTRUCTION: the unsupervised loop is the
        # very code the off arms above already timed — there is no
        # supervision branch on that path to pay for. Arms alternate,
        # median-of-3, same as the recorder A/B.
        from paddle_tpu.serving import RestartPolicy

        sup_on, sup_off = [], []
        for _ in range(3):
            sup_on.append(serve_pass(None, supervise=RestartPolicy(),
                                     step_timeout_s=300.0)[0])
            sup_off.append(serve_pass(None)[0])
        sup_overhead_pct = round(
            (median(sup_off) - median(sup_on)) / median(sup_off) * 100, 2)

        # metrics-store A/B (SLO sensor layer): the same prompts
        # re-served with the in-process time-series store attached —
        # the loop feeds every gauge/counter as monotonic-stamped
        # samples (interval-throttled) and the token hot path appends
        # per-tenant latency samples. Budget: <2% tok/s (the flight
        # recorder's budget — the off path is one detached-attribute
        # check per site). Arms alternate, median-of-3, same protocol
        # as the recorder A/B.
        ms_on, ms_off = [], []
        for _ in range(3):
            ms_on.append(serve_pass(None, metrics_store=True)[0])
            ms_off.append(serve_pass(None)[0])
        ms_overhead_pct = round(
            (median(ms_off) - median(ms_on)) / median(ms_off) * 100, 2)

        # trace-context A/B (distributed tracing): the same prompts
        # re-served with per-request TraceContext minting disabled.
        # The stamp is one uuid4 mint + a frozen dataclass per REQUEST
        # (nothing on the per-token path), so the honest budget is the
        # recorder's <2% tok/s with lots of headroom. Arms alternate,
        # median-of-3, same protocol as the recorder A/B.
        tc_on, tc_off = [], []
        for _ in range(3):
            tc_on.append(serve_pass(None)[0])
            tc_off.append(serve_pass(None, trace_context=False)[0])
        tc_overhead_pct = round(
            (median(tc_off) - median(tc_on)) / median(tc_off) * 100, 2)

        # multi-step on-device decode A/B (ROADMAP item 6): the same
        # prompts re-served through fused engines at readout_stride=k
        # vs 1, with per-arm rtt/dispatch/host-sync shares read off the
        # flight recorder — the host-tax split this PR exists to shrink.
        ms_stride = int(os.environ.get("BENCH_READOUT_STRIDE", "8"))
        multi_ab = _serve_multi_step_ab(
            model, prompts, new_tokens, B, cap, ms_stride, rtt_s=rtt)
        art_dir = _artifact_dir()
        stem = "llama_serve"
        trace_path = os.path.join(art_dir, f"{stem}_trace.json")
        recorder.export_chrome_trace(trace_path)
        tail_p99 = recorder.explain_tail(0.99, top=64)
        rec_snap = recorder.snapshot(tail=tail_p99)
        tel_path = os.path.join(art_dir, f"{stem}_telemetry.json")
        with open(tel_path, "w") as f:
            json.dump({
                "telemetry": server_on.telemetry.snapshot(wall_s=wall_on),
                "flight_recorder": rec_snap,
                "explain_tail_p99": tail_p99[:8],
            }, f, indent=1)
        # r05 sync-loop baseline (BENCH_r05.json): serve 1,158.9 tok/s —
        # comparable ONLY at the exact captured config (on-chip
        # defaults, bf16); any overridden knob makes the ratio
        # meaningless, so it degrades to null like the other bench lines
        at_r05_config = (
            B == 8 and new_tokens == 64
            and n_req == 16 and n_layers == 3
            and hidden == 4096 and ff == hidden * 11 // 4
            and horizon == 64 and not weight_dtype
            and jax.default_backend() != "cpu")
        base_toks = 1158.9
        out = {"metric": "llama_serve_tokens_per_sec",
               "value": round(toks / wall, 1), "unit": "tokens/s",
               "vs_baseline": (round(toks / wall / base_toks, 4)
                               if at_r05_config else None),
               "requests_per_sec": round(n_req / wall, 2),
               "steps_per_sec": round(steps / wall, 1),
               "requests": n_req, "slots": B,
               "prompt_lens": f"{min(len(p) for p in prompts)}-"
                              f"{max(len(p) for p in prompts)}",
               "new_tokens": new_tokens,
               "prefill_chunks": stats_off["prefill_chunks"],
               "horizon": horizon,
               "pipeline_depth": server.pipeline_depth,
               # recorder-on A/B (budget: < 2% tok/s regression) + the
               # persisted observability artifacts
               "flight_recorder_overhead_pct": rec_overhead_pct,
               "flight_recorder_on_tokens_per_sec": round(tps_on, 1),
               # supervision A/B (budget: < 1% tok/s — one heartbeat
               # read per loop pass; off-arm overhead is 0 by
               # construction). Restart-recovery wall time is measured
               # by tests/test_faults.py's chaos matrix and persisted
               # at the artifact path below.
               "supervision_overhead_pct": sup_overhead_pct,
               "supervision_on_tokens_per_sec": round(median(sup_on), 1),
               # metrics-store A/B (budget: < 2% tok/s — ring appends
               # + throttled gauge feeds; off path is one detached-
               # attribute check, same pattern as the recorder)
               "metrics_store_overhead_pct": ms_overhead_pct,
               "metrics_store_on_tokens_per_sec": round(
                   median(ms_on), 1),
               # trace-context A/B (budget: < 2% tok/s — one context
               # mint per request, nothing per token)
               "trace_context_overhead_pct": tc_overhead_pct,
               "trace_context_on_tokens_per_sec": round(
                   median(tc_on), 1),
               "restart_recovery_artifact": os.path.join(
                   art_dir, "restart_recovery.json"),
               "tail_causes_p99": rec_snap["tail_causes_p99"],
               "trace_artifact": trace_path,
               "telemetry_artifact": tel_path,
               # per-stage wall attribution from the serving telemetry —
               # replaces the one-scalar RTT split that left ~76% of r05
               # serve wall unexplained
               "attributed_share": att["attributed_share"],
               "stage_share": att["stage_share"],
               "ttft_p50_ms": round(lat["ttft"]["p50_s"] * 1e3, 1),
               "e2e_p50_ms": round(lat["e2e"]["p50_s"] * 1e3, 1),
               "rtt_est_ms": round(rtt * 1e3, 1),
               # host-RTT share of the serve wall (rtt x host passes /
               # wall) — the r05 tax this line tracks the TREND of:
               # llama_serve 0.233 at r05
               "rtt_share": round(rtt * steps / wall, 4),
               "rtt_share_r05": 0.233,
               "weight_dtype": weight_dtype or "bf16"}
        if multi_ab is not None:
            # the multi-step decode A/B: speedup + per-arm host-tax
            # split. The stride arm's host_sync + dispatch tax must sit
            # strictly below the stride-off arm's — tier-1's CPU smoke
            # asserts the structurally-stride-tied components (round
            # trips, rtt share, host_sync share); the dispatch-inclusive
            # comparison is meaningful where dispatch is a pure enqueue
            # (TPU), see _serve_multi_step_ab's docstring
            out["multi_step_speedup"] = multi_ab["multi_step_speedup"]
            out["multi_step"] = multi_ab
        return out

    if model_name == "llama_serve_fused":
        # Fused chunked-prefill + decode scheduling A/B: the SAME model /
        # prompts / server loop served by LLMEngine(scheduler="fused")
        # (Sarathi-style token-budget mixed steps — admission is slot
        # assignment, prefill chunks interleave INTO the decode batch,
        # one dispatch per engine step) vs the legacy admit-then-decode
        # scheduler whose prompt-long prefill trains stall every running
        # decode. Alongside throughput the line records the two numbers
        # the scheduler exists to move: admission_stall (queued-after-
        # free-slot time) and ramp-in dispatch counts.
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_req = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        cap = 512 + new_tokens
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        horizon = int(os.environ.get("BENCH_HORIZON", "64"))
        max_step_tokens = int(os.environ.get("BENCH_MAX_STEP_TOKENS", "0"))
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        lens = [256 + int(x) for x in rng.integers(0, 256, size=n_req)]
        prompts = [rng.integers(0, cfg.vocab_size, (L,)).astype(np.int32)
                   for L in lens]

        arm_snapshots = {}

        def run_arm(scheduler):
            kw = dict(max_batch=B, max_seq_len=cap, chunk_size=chunk,
                      horizon=horizon, scheduler=scheduler)
            if scheduler == "fused" and max_step_tokens:
                kw["max_step_tokens"] = max_step_tokens
            eng = LLMEngine(model, **kw)
            eng.generate([prompts[0]], max_new_tokens=2)  # warm programs
            eng.reset_stats()
            server = AsyncLLMServer(eng, max_queue_size=n_req + 1)
            server.start()
            t0 = time.perf_counter()
            handles = [server.submit(p, max_new_tokens=new_tokens)
                       for p in prompts]
            outs = [h.result(timeout=1800) for h in handles]
            wall = time.perf_counter() - t0
            server.stop()
            toks = sum(len(o.token_ids) for o in outs)
            snap = server.telemetry.snapshot(wall_s=wall)
            arm_snapshots[scheduler] = snap
            stall = snap["latency"]["admission_stall"]
            return {
                "tokens_per_sec": toks / wall,
                "admission_stall_p50_ms": round(stall["p50_s"] * 1e3, 1),
                "admission_stall_p90_ms": round(stall["p90_s"] * 1e3, 1),
                "prefill_token_share": snap["prefill_token_share"],
                "ttft_p50_ms": round(
                    snap["latency"]["ttft"]["p50_s"] * 1e3, 1),
                "attributed_share": snap["attribution"]["attributed_share"],
                # ramp-in dispatch shape: legacy = prefill_chunks IS the
                # dispatch count (one serial dispatch per chunk inside
                # _admit, decodes stalled behind the train); fused = the
                # same chunk grants ride inside fused_steps MIXED
                # dispatches (1 per engine step, decodes riding along)
                "prefill_chunks": eng.stats["prefill_chunks"],
                "ramp_dispatches": (eng.stats["fused_steps"]
                                    if scheduler == "fused"
                                    else eng.stats["prefill_chunks"]),
                "fused_steps": eng.stats["fused_steps"],
                "engine_steps": eng.stats["steps"],
            }

        fused = run_arm("fused")
        legacy = run_arm("legacy")
        # persist the fused arm's full telemetry snapshot next to the
        # bench output (same artifact dir as the llama_serve recorder
        # dump) so stall/share regressions can be diffed without a re-run
        fused_tel_path = os.path.join(_artifact_dir(),
                                      "llama_serve_fused_telemetry.json")
        with open(fused_tel_path, "w") as f:
            json.dump({"fused": fused, "legacy": legacy,
                       "snapshots": arm_snapshots}, f, indent=1)
        at_r05_config = (
            B == 8 and new_tokens == 64 and n_req == 16 and n_layers == 3
            and hidden == 4096 and ff == hidden * 11 // 4
            and horizon == 64 and chunk == 256 and not max_step_tokens
            and jax.default_backend() != "cpu")
        return {"metric": "llama_serve_fused_tokens_per_sec",
                "value": round(fused["tokens_per_sec"], 1),
                "unit": "tokens/s",
                # r05 sync-loop serve baseline (BENCH_r05.json): 1,158.9
                # tok/s at this exact captured config
                "vs_baseline": (round(fused["tokens_per_sec"] / 1158.9, 4)
                                if at_r05_config else None),
                "scheduler_on": fused,
                "scheduler_off": legacy,
                "scheduler_speedup": round(
                    fused["tokens_per_sec"]
                    / max(legacy["tokens_per_sec"], 1e-9), 3),
                "requests": n_req, "slots": B, "new_tokens": new_tokens,
                "prompt_lens": f"{min(lens)}-{max(lens)}",
                "chunk": chunk, "horizon": horizon,
                "max_step_tokens": max_step_tokens or chunk + B - 1,
                "telemetry_artifact": fused_tel_path}

    if model_name == "llama_serve_prefix_cache":
        # Automatic prefix caching A/B: the SAME model / server served by
        # LLMEngine(cache_impl="paged", scheduler="fused") with
        # enable_prefix_cache on vs off, on TWO workloads:
        #   * shared — every prompt opens with the same system prompt
        #     (the template-heavy production shape): cache-on should
        #     report hit_rate > 0 and tokens/s >= cache-off, since the
        #     shared span admits as pure table writes + refcount bumps
        #     (zero prefill FLOPs);
        #   * zero-reuse — all-unique prompts: the overhead guard. The
        #     hash-chain probe, registration, and LRU bookkeeping ride
        #     the admission path, so cache-on must stay within 2% of
        #     cache-off here.
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_req = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        block = int(os.environ.get("BENCH_BLOCK", "64"))
        horizon = int(os.environ.get("BENCH_HORIZON", "64"))
        sys_len = int(os.environ.get("BENCH_SYS_PROMPT", "256"))
        tail_len = int(os.environ.get("BENCH_TAIL", "128"))
        # paged KV needs capacity % chunk == 0
        cap = -(-(sys_len + tail_len + new_tokens) // chunk) * chunk
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        V = cfg.vocab_size
        sys_prompt = rng.integers(0, V, (sys_len,)).astype(np.int32)
        tails = [rng.integers(0, V, (tail_len // 2 + int(x),)).astype(
            np.int32) for x in rng.integers(0, tail_len // 2, size=n_req)]
        shared = [np.concatenate([sys_prompt, t]) for t in tails]
        unique = [rng.integers(0, V, (sys_len + len(t),)).astype(np.int32)
                  for t in tails]

        def run_arm(prompts, cache_on):
            eng = LLMEngine(model, max_batch=B, max_seq_len=cap,
                            chunk_size=chunk, horizon=horizon,
                            cache_impl="paged", block_size=block,
                            scheduler="fused",
                            enable_prefix_cache=cache_on)
            # warm the compiled programs with a throwaway prompt that
            # shares nothing with the workload (must not seed the cache)
            warm = rng.integers(0, V, (3,)).astype(np.int32)
            eng.generate([warm], max_new_tokens=2)
            eng.reset_stats()
            server = AsyncLLMServer(eng, max_queue_size=n_req + 1)
            server.start()
            t0 = time.perf_counter()
            handles = [server.submit(p, max_new_tokens=new_tokens)
                       for p in prompts]
            outs = [h.result(timeout=1800) for h in handles]
            wall = time.perf_counter() - t0
            server.stop()
            toks = sum(len(o.token_ids) for o in outs)
            snap = server.telemetry.snapshot(wall_s=wall)
            hit = eng.stats["prefix_hit_tokens"]
            pre = eng.stats["prefill_tokens"]
            return {
                "tokens_per_sec": toks / wall,
                "hit_rate": round(hit / (hit + pre), 4) if hit + pre
                else 0.0,
                "prefix_hit_tokens": hit,
                "prefill_tokens": pre,
                "cow_blocks": eng.stats["prefix_cow_blocks"],
                "evicted_blocks": eng.stats["prefix_evicted_blocks"],
                "ttft_p50_ms": round(
                    snap["latency"]["ttft"]["p50_s"] * 1e3, 1),
                "attributed_share": snap["attribution"]["attributed_share"],
            }, [list(o.token_ids) for o in outs]

        shared_on, toks_on = run_arm(shared, True)
        shared_off, toks_off = run_arm(shared, False)
        unique_on, _ = run_arm(unique, True)
        unique_off, _ = run_arm(unique, False)
        overhead_pct = round(
            (1.0 - unique_on["tokens_per_sec"]
             / max(unique_off["tokens_per_sec"], 1e-9)) * 100, 2)
        art_path = os.path.join(_artifact_dir(),
                                "llama_serve_prefix_cache.json")
        with open(art_path, "w") as f:
            json.dump({"shared_on": shared_on, "shared_off": shared_off,
                       "unique_on": unique_on, "unique_off": unique_off},
                      f, indent=1)
        return {"metric": "llama_serve_prefix_cache_tokens_per_sec",
                "value": round(shared_on["tokens_per_sec"], 1),
                "unit": "tokens/s", "vs_baseline": None,
                "cache_on": shared_on, "cache_off": shared_off,
                "prefix_cache_speedup": round(
                    shared_on["tokens_per_sec"]
                    / max(shared_off["tokens_per_sec"], 1e-9), 3),
                # greedy serving: the A/B must be token-exact too
                "token_parity": toks_on == toks_off,
                "zero_reuse_on": unique_on, "zero_reuse_off": unique_off,
                "zero_reuse_overhead_pct": overhead_pct,
                "requests": n_req, "slots": B, "new_tokens": new_tokens,
                "sys_prompt_len": sys_len, "chunk": chunk,
                "block_size": block, "horizon": horizon,
                "telemetry_artifact": art_path}

    if model_name == "llama_serve_kv_quant":
        # Quantized-KV serving A/B: the SAME model/workload served by
        # LLMEngine(cache_impl="paged", scheduler="fused") with the pool
        # at bf16 vs int8 vs int4 — every arm's pool sized to the SAME
        # HBM BYTE BUDGET (the bf16 arm's oversubscribed pool bytes), so
        # the quantized arms hold ~2x/~4x the blocks. What the capacity
        # buys shows up as fewer preemptions / more resident slots /
        # higher tok/s on the memory-bound decode phase; what it costs
        # shows up in the greedy token-drift metric vs the bf16 arm
        # (exact-match prefix length + first divergence step per
        # request).
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_req = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        block = int(os.environ.get("BENCH_BLOCK", "64"))
        prompt_len = int(os.environ.get("BENCH_PROMPT", "256"))
        # the bf16 arm's pool covers this fraction of the full
        # (never-preempts) block demand — <1 = oversubscribed, so the
        # capacity lever has preemptions to convert into residency
        pool_frac = float(os.environ.get("BENCH_POOL_FRAC", "0.5"))
        cap = -(-(prompt_len + new_tokens) // chunk) * chunk
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        V = cfg.vocab_size
        prompts = [rng.integers(0, V, (prompt_len - 7 + int(x),)).astype(
            np.int32) for x in rng.integers(0, 15, size=n_req)]
        full_blocks = B * (cap // block)
        bf16_blocks = max(int(full_blocks * pool_frac), B + 1)

        _bpb_cache = {}

        def pool_blocks_for(dtype):
            # equal-HBM sizing through the engine's own byte arithmetic
            # (kv_bytes_per_block counts payload + scale arrays) — one
            # minimum-size probe engine per dtype, memoized
            if dtype not in _bpb_cache:
                probe = LLMEngine(model, max_batch=B, max_seq_len=cap,
                                  chunk_size=chunk, cache_impl="paged",
                                  block_size=block, scheduler="fused",
                                  kv_pool_blocks=B + 1,
                                  kv_cache_dtype=dtype)
                _bpb_cache[dtype] = probe.kv_bytes_per_block()
                del probe
            return _bpb_cache[dtype]

        budget = bf16_blocks * pool_blocks_for(None)

        def run_arm(dtype):
            n_blocks = min(budget // pool_blocks_for(dtype), full_blocks)
            eng = LLMEngine(model, max_batch=B, max_seq_len=cap,
                            chunk_size=chunk, cache_impl="paged",
                            block_size=block, scheduler="fused",
                            kv_pool_blocks=n_blocks, kv_cache_dtype=dtype)
            warm = rng.integers(0, V, (3,)).astype(np.int32)
            eng.generate([warm], max_new_tokens=2)
            eng.reset_stats()
            server = AsyncLLMServer(eng, max_queue_size=n_req + 1)
            server.start()
            t0 = time.perf_counter()
            handles = [server.submit(p, max_new_tokens=new_tokens)
                       for p in prompts]
            slot_samples = []
            outs = []
            # short result polls double as resident-slot samples; the
            # wall deadline keeps a pathological config (e.g. a pool
            # oversubscribed into ramp thrash) a loud failure, not a
            # hang
            deadline = t0 + 1800
            for h in handles:
                while True:
                    try:
                        outs.append(h.result(timeout=0.05))
                        break
                    except TimeoutError:
                        if time.perf_counter() > deadline:
                            raise
                        slot_samples.append(
                            sum(1 for s in eng.slots if s is not None))
            wall = time.perf_counter() - t0
            server.stop()
            toks = sum(len(o.token_ids) for o in outs)
            return {
                "kv_cache_dtype": dtype or "bf16",
                "tokens_per_sec": round(toks / wall, 1),
                "pool_blocks": n_blocks,
                "effective_blocks": eng.kv_pool_effective_blocks(),
                "pool_bytes": eng.kv_pool_nbytes(),
                "preemptions": eng.stats["preemptions"],
                "mean_resident_slots": round(
                    float(np.mean(slot_samples)) if slot_samples else
                    float(B), 2),
            }, [list(o.token_ids) for o in outs]

        def drift(ref_toks, arm_toks):
            # greedy drift vs the bf16 arm: exact-match prefix length and
            # the first divergence step, per request
            prefixes, first_div = [], None
            for ref, got in zip(ref_toks, arm_toks):
                n = 0
                for a, b2 in zip(ref, got):
                    if a != b2:
                        break
                    n += 1
                prefixes.append(n)
                if (n < min(len(ref), len(got)) or len(ref) != len(got)) \
                        and (first_div is None or n < first_div):
                    first_div = n
            return {"min_match_prefix": int(min(prefixes)),
                    "mean_match_prefix": round(float(np.mean(prefixes)), 1),
                    "first_divergence_step": first_div,
                    "token_parity": first_div is None}

        bf16_arm, bf16_toks = run_arm(None)
        int8_arm, int8_toks = run_arm("int8")
        int4_arm, int4_toks = run_arm("int4")
        int8_arm["drift_vs_bf16"] = drift(bf16_toks, int8_toks)
        int4_arm["drift_vs_bf16"] = drift(bf16_toks, int4_toks)
        art_path = os.path.join(_artifact_dir(), "llama_serve_kv_quant.json")
        with open(art_path, "w") as f:
            json.dump({"bf16": bf16_arm, "int8": int8_arm,
                       "int4": int4_arm}, f, indent=1)
        return {"metric": "llama_serve_kv_quant_tokens_per_sec",
                "value": int8_arm["tokens_per_sec"],
                "unit": "tokens/s", "vs_baseline": None,
                "bf16": bf16_arm, "int8": int8_arm, "int4": int4_arm,
                "int8_speedup": round(
                    int8_arm["tokens_per_sec"]
                    / max(bf16_arm["tokens_per_sec"], 1e-9), 3),
                "int4_speedup": round(
                    int4_arm["tokens_per_sec"]
                    / max(bf16_arm["tokens_per_sec"], 1e-9), 3),
                "requests": n_req, "slots": B, "new_tokens": new_tokens,
                "prompt_len": prompt_len, "chunk": chunk,
                "block_size": block, "pool_frac": pool_frac,
                "full_blocks": full_blocks,
                "telemetry_artifact": art_path}

    if model_name == "llama_serve_kv_tier":
        # Host KV-tier A/B: the SAME model/workload/pool served with the
        # tier OFF (preemption = full re-prefill, eviction = discard) vs
        # ON (kv_host_swap: preempted slots round-trip host RAM;
        # kv_host_spill_bytes: evicted prefix blocks spill + promote) at
        # EQUAL device-pool bytes — the tier spends host RAM and PCIe/DMA
        # bandwidth, never device HBM, so any tok/s win is pure recompute
        # avoided. The workload is the shape the tier serves in
        # production: TWO groups of requests each sharing a long system
        # prompt (BENCH_SYS_FRAC of the prompt) with unique tails,
        # interleaved so the groups CHURN each other's shared blocks out
        # of the pressured pool — the off arm recomputes the shared
        # prefix every time it cycles back, the on arm promotes it from
        # the host spill store (and preempted slots restore instead of
        # re-prefilling). What the tier buys shows up as re-prefill
        # tokens avoided and fewer prefill dispatches, what it costs as
        # the swap-stall share of serve wall. Streams must stay
        # TOKEN-EXACT across arms (the copies restore the bytes the pool
        # held). CPU-shape caveat: a toy-model serve is DISPATCH-bound
        # and decode-step-count invariant, so avoided prefill tokens
        # barely move tok/s there (expect ~parity inside the ±5% CPU
        # noise band, with the re-prefill reduction as the attributable
        # win); the tok/s gap opens on shapes where prefill FLOPs
        # dominate the copies — real model sizes on real accelerators.
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_req = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        block = int(os.environ.get("BENCH_BLOCK", "64"))
        prompt_len = int(os.environ.get("BENCH_PROMPT", "256"))
        pool_frac = float(os.environ.get("BENCH_POOL_FRAC", "0.5"))
        spill_mb = int(os.environ.get("BENCH_SPILL_MB", "256"))
        sys_frac = float(os.environ.get("BENCH_SYS_FRAC", "0.6"))
        cap = -(-(prompt_len + new_tokens) // chunk) * chunk
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        V = cfg.vocab_size
        sys_len = int(prompt_len * sys_frac)
        sys_prompts = [rng.integers(0, V, (sys_len,)).astype(np.int32)
                       for _ in range(2)]
        prompts = [np.concatenate([
            sys_prompts[i % 2],
            rng.integers(0, V, (prompt_len - sys_len - 7 + int(x),))
            .astype(np.int32)])
            for i, x in enumerate(rng.integers(0, 15, size=n_req))]
        full_blocks = B * (cap // block)
        n_blocks = max(int(full_blocks * pool_frac), B + 1)

        def run_arm(tier_on, pool_blocks=None):
            eng = LLMEngine(
                model, max_batch=B, max_seq_len=cap, chunk_size=chunk,
                cache_impl="paged", block_size=block, scheduler="fused",
                kv_pool_blocks=pool_blocks or n_blocks,
                enable_prefix_cache=True,
                kv_host_swap=tier_on,
                kv_host_spill_bytes=(spill_mb << 20) if tier_on else 0)
            warm = rng.integers(0, V, (3,)).astype(np.int32)
            eng.generate([warm], max_new_tokens=2)
            eng.reset()
            eng.reset_stats()
            server = AsyncLLMServer(eng, max_queue_size=n_req + 1)
            server.start()
            t0 = time.perf_counter()
            handles = [server.submit(p, max_new_tokens=new_tokens)
                       for p in prompts]
            outs = []
            deadline = t0 + 1800     # a thrashing config fails loudly
            for h in handles:
                while True:
                    try:
                        outs.append(h.result(timeout=0.05))
                        break
                    except TimeoutError:
                        if time.perf_counter() > deadline:
                            raise
            wall = time.perf_counter() - t0
            server.stop()
            toks = sum(len(o.token_ids) for o in outs)
            s = eng.stats
            swap_stall = s["swap_out_time_s"] + s["swap_in_time_s"]
            return {
                "tier": "on" if tier_on else "off",
                "tokens_per_sec": round(toks / wall, 1),
                "pool_blocks": pool_blocks or n_blocks,
                "preemptions": s["preemptions"],
                "prefill_tokens": s["prefill_tokens"],
                "prefix_hit_tokens": s["prefix_hit_tokens"],
                "kv_swap_out_blocks": s["kv_swap_out_blocks"],
                "kv_swap_in_blocks": s["kv_swap_in_blocks"],
                "kv_swap_saved_tokens": s["kv_swap_saved_tokens"],
                "kv_spill_blocks": s["kv_spill_blocks"],
                "kv_promote_blocks": s["kv_promote_blocks"],
                "swap_stall_share": round(swap_stall / max(wall, 1e-9), 4),
            }, [list(o.token_ids) for o in outs]

        # the FLOOR arm (full pool, tier off): the prefill tokens an
        # unpressured prefix-cached serve of this workload dispatches —
        # no preemptions, no evictions. Everything a pressured arm
        # dispatches beyond it is RE-prefill (recompute of KV the
        # engine already produced), which is exactly what the tier
        # exists to remove; the floor also anchors token parity.
        floor_arm, floor_toks = run_arm(False, pool_blocks=full_blocks)
        off_arm, off_toks = run_arm(False)
        on_arm, on_toks = run_arm(True)
        floor = floor_arm["prefill_tokens"]
        re_off = max(off_arm["prefill_tokens"] - floor, 0)
        re_on = max(on_arm["prefill_tokens"] - floor, 0)
        art_path = os.path.join(_artifact_dir(), "llama_serve_kv_tier.json")
        with open(art_path, "w") as f:
            json.dump({"floor": floor_arm, "tier_off": off_arm,
                       "tier_on": on_arm,
                       "reprefill_tokens_off": re_off,
                       "reprefill_tokens_on": re_on}, f, indent=1)
        return {"metric": "llama_serve_kv_tier_tokens_per_sec",
                "value": on_arm["tokens_per_sec"],
                "unit": "tokens/s", "vs_baseline": None,
                "floor": floor_arm, "tier_off": off_arm,
                "tier_on": on_arm,
                "tiering_speedup": round(
                    on_arm["tokens_per_sec"]
                    / max(off_arm["tokens_per_sec"], 1e-9), 3),
                "reprefill_tokens_off": re_off,
                "reprefill_tokens_on": re_on,
                "reprefill_reduction": round(
                    (re_off - re_on) / re_off, 3) if re_off else None,
                "token_parity": off_toks == on_toks == floor_toks,
                "requests": n_req, "slots": B, "new_tokens": new_tokens,
                "prompt_len": prompt_len, "sys_frac": sys_frac,
                "chunk": chunk,
                "block_size": block, "pool_frac": pool_frac,
                "spill_mb": spill_mb, "full_blocks": full_blocks,
                "telemetry_artifact": art_path}

    if model_name == "llama_serve_disagg":
        # Disaggregated prefill/decode A/B (DistServe/Splitwise): the
        # SAME two-replica fleet and workload served with role-split
        # routing (1 prefill + 1 decode replica; finished prefills SHIP
        # their staged KV to the decode replica and resume with the
        # one-token stitch — zero re-prefill) vs mixed placement (both
        # replicas take everything). The workload is the interference
        # shape disaggregation exists for: a PREFILL FLOOD of long-
        # prompt/short-output requests landing while a handful of
        # DECODE-TRICKLE streams are mid-generation. Mixed placement
        # lets the flood's chunk grants ride the tricklers' decode
        # steps (Sarathi interference on both replicas); the split arm
        # keeps the decode replica's steps prefill-free except the
        # stitch. What the split buys shows up as decode inter-token
        # p99 and TTFT p99 under flood; what it costs as shipped bytes
        # and the migration-latency histogram. Streams must stay
        # TOKEN-EXACT across arms (greedy: placement cannot change
        # tokens). An unflooded floor arm (same fleet, trickle only)
        # anchors the p99s. CPU-shape caveat: toy-model steps are
        # dispatch-bound, so the split's p99 win is muted vs real
        # accelerators where a long-prompt chunk occupies the device
        # for whole milliseconds.
        import threading
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer, ReplicaRouter
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        flood_n = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        trickle_n = int(os.environ.get("BENCH_TRICKLE", "4"))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        block = int(os.environ.get("BENCH_BLOCK", "64"))
        prompt_len = int(os.environ.get("BENCH_PROMPT", "256"))
        cap = -(-(prompt_len + new_tokens) // chunk) * chunk
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        V = cfg.vocab_size
        flood_prompts = [rng.integers(0, V, (prompt_len - 7 + int(x),))
                         .astype(np.int32)
                         for x in rng.integers(0, 15, size=flood_n)]
        trickle_prompts = [rng.integers(0, V, (max(prompt_len // 4, 4),))
                           .astype(np.int32) for _ in range(trickle_n)]

        from paddle_tpu.profiler import FlightRecorder

        def run_arm(roles, flood=True, trace_path=None):
            servers = []
            for i in range(2):
                eng = LLMEngine(
                    model, max_batch=B, max_seq_len=cap,
                    chunk_size=chunk, cache_impl="paged",
                    block_size=block, scheduler="fused")
                warm = rng.integers(0, V, (3,)).astype(np.int32)
                eng.generate([warm], max_new_tokens=2)
                eng.reset()
                eng.reset_stats()
                servers.append(AsyncLLMServer(
                    eng, replica=i,
                    flight_recorder=(FlightRecorder()
                                     if trace_path else None),
                    max_queue_size=flood_n + trickle_n + 1))
            router = ReplicaRouter(servers, roles=roles)
            router.start()
            t0 = time.perf_counter()
            stamps = [[] for _ in range(trickle_n)]
            t_sub = [None] * trickle_n

            def consume(h, out):
                for tok in h:
                    out.append((time.perf_counter(), int(tok)))

            threads = []
            for i, p in enumerate(trickle_prompts):
                t_sub[i] = time.perf_counter()
                h = router.submit(p, max_new_tokens=new_tokens)
                th = threading.Thread(target=consume,
                                      args=(h, stamps[i]), daemon=True)
                th.start()
                threads.append(th)
            flood_handles = [router.submit(p, max_new_tokens=2)
                             for p in flood_prompts] if flood else []
            flood_toks = [list(h.result(timeout=1800).token_ids)
                          for h in flood_handles]
            for th in threads:
                th.join(timeout=1800)
            wall = time.perf_counter() - t0
            snap = router.snapshot()
            if trace_path:
                # the stitched cross-replica trace: every migrated
                # request's prefill and decode legs flow-linked into one
                # Perfetto chain, plus the router:migrations phase lane
                router.export_merged_trace(trace_path)
            router.stop(timeout=120)
            gaps = [b[0] - a[0] for s in stamps
                    for a, b in zip(s, s[1:])]
            ttfts = [s[0][0] - t for s, t in zip(stamps, t_sub) if s]
            toks = sum(len(s) for s in stamps) + \
                sum(len(t) for t in flood_toks)
            # re-prefill paid by DECODE-role steps: with roles, every
            # migrated request books exactly its one-token stitch on
            # the decode replica — anything beyond is fallback work
            migrated = router.stats["kv_shipped"] + \
                router.stats["kv_ship_fallback"]
            decode_prefill = servers[1].engine.stats["prefill_tokens"] \
                if roles else None
            out = {
                "arm": ("disagg" if roles else
                        "mixed" if flood else "floor"),
                "tokens_per_sec": round(toks / wall, 1),
                "decode_p99_ms": round(float(np.quantile(
                    gaps, 0.99)) * 1000, 3) if gaps else None,
                "decode_p50_ms": round(float(np.quantile(
                    gaps, 0.50)) * 1000, 3) if gaps else None,
                "ttft_p99_ms": round(float(np.quantile(
                    ttfts, 0.99)) * 1000, 3) if ttfts else None,
                "kv_shipped": router.stats["kv_shipped"],
                "kv_ship_fallback": router.stats["kv_ship_fallback"],
                "ship_bytes": snap["transport"]["ship_bytes"]
                if snap.get("transport") else 0,
                "migration_latency": snap.get("migration_latency"),
                "migration_phases": snap.get("migration_phases"),
                "decode_reprefill_tokens": (decode_prefill - migrated)
                if decode_prefill is not None else None,
            }
            return out, [[int(t) for _, t in s] for s in stamps], \
                flood_toks

        roles = {"prefill": [0], "decode": [1]}
        floor_arm, floor_trickle, _ = run_arm(None, flood=False)
        mixed_arm, mixed_trickle, mixed_flood = run_arm(None)
        trace_path = os.path.join(_artifact_dir(),
                                  "llama_serve_disagg_trace.json")
        dis_arm, dis_trickle, dis_flood = run_arm(roles,
                                                  trace_path=trace_path)
        parity = (dis_trickle == mixed_trickle == floor_trickle
                  and dis_flood == mixed_flood)
        # the phase sub-spans must ACCOUNT for the measured migration
        # latency: they nest inside the t0..t1 window (never exceed it
        # beyond timer noise) and explain at least half of it — the
        # un-phased residual is placement ranking + handle bookkeeping.
        # Only a clean ship run is comparable (a fallback books latency
        # with no phases and would dilute the histogram means).
        mp = dis_arm["migration_phases"] or {}
        phase_sum = sum(mp[p]["mean_s"]
                        for p in ("serialize", "transport", "import",
                                  "place") if p in mp)
        mig_mean = (dis_arm["migration_latency"] or {}).get("mean_s", 0)
        if dis_arm["kv_shipped"] and not dis_arm["kv_ship_fallback"]:
            assert 0.5 * mig_mean <= phase_sum <= 1.05 * mig_mean, \
                (phase_sum, mig_mean, mp)
        art_path = os.path.join(_artifact_dir(),
                                "llama_serve_disagg.json")
        with open(art_path, "w") as f:
            json.dump({"floor": floor_arm, "mixed": mixed_arm,
                       "disagg": dis_arm, "token_parity": parity,
                       "migration_phase_sum_s": round(phase_sum, 6),
                       "trace_artifact": trace_path},
                      f, indent=1)
        return {"metric": "llama_serve_disagg_decode_p99_ms",
                "value": dis_arm["decode_p99_ms"],
                "unit": "ms", "vs_baseline": None,
                "floor": floor_arm, "mixed": mixed_arm,
                "disagg": dis_arm,
                "disagg_p99_vs_mixed": round(
                    dis_arm["decode_p99_ms"]
                    / max(mixed_arm["decode_p99_ms"], 1e-9), 3),
                "token_parity": parity,
                "flood_requests": flood_n, "trickle_requests": trickle_n,
                "slots": B, "new_tokens": new_tokens,
                "prompt_len": prompt_len, "chunk": chunk,
                "block_size": block,
                "migration_phase_sum_s": round(phase_sum, 6),
                "trace_artifact": trace_path,
                "telemetry_artifact": art_path}

    if model_name == "llama_serve_slo":
        # Multi-tenant SLO isolation bench (the sensor half of ROADMAP
        # item 4): an ADVERSARIAL tenant floods the queue with long
        # prompts while a well-behaved VICTIM tenant keeps streaming
        # short requests. The new per-tenant latency histograms measure
        # the victim's p99 TTFT SEPARATELY from the adversary's (the
        # global histogram would blend them), a calibrated
        # SLO(metric="ttft_p99", tenant=victim) watches the victim from
        # the metrics store, and the Google-SRE multi-window burn-rate
        # alert must FIRE during the flood and CLEAR after it drains.
        # The final slo_report + the burn-rate trajectory persist to
        # docs/artifacts/slo_report.json — the evidence the PR-15+ SLO
        # controller will close its loop against.
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import (AdapterStore, AsyncLLMServer,
                                        random_lora_weights)
        from paddle_tpu.profiler import SLO, FlightRecorder
        B = int(os.environ.get("BENCH_BATCH", "4"))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        block = int(os.environ.get("BENCH_BLOCK", "64"))
        victim_prompt = int(os.environ.get("BENCH_VICTIM_PROMPT", "32"))
        victim_new = int(os.environ.get("BENCH_VICTIM_NEW_TOKENS", "12"))
        flood_prompt = int(os.environ.get("BENCH_FLOOD_PROMPT", "256"))
        flood_new = int(os.environ.get("BENCH_FLOOD_NEW_TOKENS", "48"))
        n_flood = int(os.environ.get("BENCH_FLOOD", "16"))
        n_warm = int(os.environ.get("BENCH_WARM", "6"))
        interval = float(os.environ.get("BENCH_VICTIM_INTERVAL_S", "0.05"))
        slow_w = float(os.environ.get("BENCH_SLO_WINDOW_S", "6.0"))
        fast_w = float(os.environ.get("BENCH_SLO_FAST_WINDOW_S", "1.5"))
        burn_thr = float(os.environ.get("BENCH_SLO_BURN", "2.0"))
        wall_deadline = float(os.environ.get("BENCH_DEADLINE_S", "900"))
        cap = -(-(max(flood_prompt, victim_prompt)
                  + max(flood_new, victim_new)) // chunk) * chunk
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        V = cfg.vocab_size
        # the adversary is a REGISTERED TENANT (adapter id) so the
        # tenant-keyed histograms and token counters split the traffic
        adapters = AdapterStore(cfg, rank=4)
        adversary = adapters.register(
            random_lora_weights(cfg, rank=4, seed=7, scale=0.02),
            alpha=1.0)
        victim = 0                      # base-model tenant
        eng = LLMEngine(model, max_batch=B, max_seq_len=cap,
                        chunk_size=chunk, cache_impl="paged",
                        block_size=block, scheduler="fused",
                        adapter_store=adapters, adapter_cache_slots=2)
        eng.generate([rng.integers(0, V, (3,)).astype(np.int32)],
                     max_new_tokens=2)          # warm the programs
        eng.reset_stats()

        def vprompt():
            return rng.integers(0, V, (victim_prompt,)).astype(np.int32)

        # -- phase 1: calibration — victim-only baseline TTFT sets the
        # SLO target (2x the observed median, floored) so the objective
        # is honest for whatever hardware runs this
        calib = AsyncLLMServer(eng, max_queue_size=n_warm + 1)
        calib.start()
        ttfts = []
        for _ in range(n_warm):
            h = calib.submit(vprompt(), max_new_tokens=victim_new)
            r = h.result(timeout=wall_deadline)
            ttfts.append(r.ttft_s)
        calib.stop()
        base_ttft = sorted(ttfts)[len(ttfts) // 2]
        target_s = max(2.0 * base_ttft, 0.02)
        slo = SLO("victim_ttft", "ttft_p99", tenant=victim,
                  target_s=target_s, window_s=slow_w,
                  fast_window_s=fast_w, burn_threshold=burn_thr)

        # -- phase 2: the flood — adversary dumps n_flood long prompts,
        # victim keeps a trickle of short requests flowing (bounded
        # outstanding so the run length stays the flood's, not ours)
        srv = AsyncLLMServer(eng, max_queue_size=n_flood + 64,
                             flight_recorder=FlightRecorder(),
                             metrics_store=True, slos=[slo],
                             metrics_interval_s=0.02, slo_interval_s=0.1)
        srv.start()
        t0 = time.monotonic()
        trajectory = []

        def poll(phase):
            (r,) = srv.slo_engine.evaluate()
            trajectory.append({
                "t_s": round(time.monotonic() - t0, 3), "phase": phase,
                "burn_rate_fast": r["burn_rate_fast"],
                "burn_rate_slow": r["burn_rate_slow"],
                "burning": r["burning"], "measured_s": r["measured_s"],
                "queue_depth": len(srv._queue)})
            return r

        flood = [srv.submit(
            rng.integers(0, V, (flood_prompt,)).astype(np.int32),
            max_new_tokens=flood_new, adapter_id=adversary)
            for _ in range(n_flood)]
        victims = []
        while any(not h.done for h in flood):
            if time.monotonic() - t0 > wall_deadline:
                raise RuntimeError(
                    f"llama_serve_slo: flood not drained after "
                    f"{wall_deadline}s — pathological config")
            if sum(1 for h in victims if not h.done) < 4:
                victims.append(srv.submit(vprompt(),
                                          max_new_tokens=victim_new))
            poll("flood")
            time.sleep(interval)
        for h in flood:
            h.result(timeout=wall_deadline)

        # -- phase 3: recovery — victim streams alone until the burn
        # alert CLEARS (bad samples age out of the fast window)
        recover_deadline = time.monotonic() + max(4 * fast_w + 10.0, 30.0)
        cleared_in_time = False
        while time.monotonic() < recover_deadline:
            h = srv.submit(vprompt(), max_new_tokens=victim_new)
            victims.append(h)
            h.result(timeout=wall_deadline)
            poll("recovery")
            burn_alerts = srv.metrics_store.alerts(kind="slo_burn")
            if burn_alerts and all(not a.active for a in burn_alerts):
                cleared_in_time = True
                break
            time.sleep(interval)
        for h in victims:
            h.result(timeout=wall_deadline)
        poll("final")
        report = srv.slo_report()
        burn_alerts = [a.to_dict()
                       for a in srv.metrics_store.alerts(kind="slo_burn")]
        srv.stop()

        fired = len(burn_alerts) > 0
        tl = report["tenant_latency"]
        vic_hist = tl[str(victim)]["ttft"]
        adv_hist = tl[str(adversary)]["ttft"]
        # the acceptance contract: the victim's p99 is measured PER
        # TENANT (its own histogram, not the blended global one — the
        # count is exactly the FLOOD SERVER's victim requests, each of
        # which streamed at least one token; the calibration server's
        # telemetry was separate), the burn alert fired under the
        # flood and cleared after it
        assert vic_hist["count"] == len(victims), \
            f"victim tenant histogram counted {vic_hist['count']} " \
            f"of {len(victims)} victim requests"
        assert adv_hist["count"] == n_flood, \
            "adversary tenant histogram miscounted the flood"
        assert fired, "burn-rate alert never fired under the flood"
        assert cleared_in_time, "burn-rate alert never cleared after"
        art_path = os.path.join(_artifact_dir(), "slo_report.json")
        with open(art_path, "w") as f:
            json.dump({
                "slo": {"name": slo.name, "metric": slo.metric,
                        "tenant": victim,
                        "target_s": round(target_s, 4),
                        "window_s": slow_w, "fast_window_s": fast_w,
                        "burn_threshold": burn_thr,
                        "calibration_ttft_p50_s": round(base_ttft, 4)},
                "report": report,
                "burn_alerts": burn_alerts,
                "trajectory": trajectory,
                "config": {"slots": B, "flood": n_flood,
                           "flood_prompt": flood_prompt,
                           "victim_prompt": victim_prompt,
                           "layers": n_layers, "hidden": hidden},
            }, f, indent=1)
        peak_burn = max(p["burn_rate_fast"] for p in trajectory)
        return {"metric": "llama_serve_slo_victim_ttft_p99_ms",
                "value": round(vic_hist["p99_s"] * 1e3, 1),
                "unit": "ms", "vs_baseline": None,
                "victim_ttft_p99_ms": round(vic_hist["p99_s"] * 1e3, 1),
                "victim_ttft_p50_ms": round(vic_hist["p50_s"] * 1e3, 1),
                "adversary_ttft_p99_ms": round(
                    adv_hist["p99_s"] * 1e3, 1),
                "target_ms": round(target_s * 1e3, 1),
                "burn_alert_fired": fired,
                "burn_alert_cleared": cleared_in_time,
                "peak_burn_rate_fast": round(peak_burn, 1),
                "trajectory_points": len(trajectory),
                "victim_requests": len(victims),
                "calibration_requests": n_warm,
                "flood_requests": n_flood,
                "pathologies_active": {k: v for k, v
                                       in report["pathologies"].items()
                                       if v},
                "slo_report_artifact": art_path}

    if model_name == "llama_serve_cluster":
        # Multichip serving A/B (paddle_tpu/serving/cluster.py): ONE
        # replica vs BENCH_REPLICAS replicas fronted by the prefix-
        # affinity ReplicaRouter, on a multi-tenant shared-system-prompt
        # workload (BENCH_TENANTS distinct system prompts, one per
        # routing_key). A third arm re-serves the cluster under RANDOM
        # routing — the affinity win (hit-rate + tok/s) is measured
        # against its own control, not inferred. BENCH_TP > 1
        # additionally shards each replica's engine over its own
        # ("tp",)-mesh device group (kv-head-sharded pools; needs
        # BENCH_REPLICAS * BENCH_TP local devices).
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer, ReplicaRouter
        from paddle_tpu.serving.cluster import tp_engine
        R = int(os.environ.get("BENCH_REPLICAS", "2"))
        tp = int(os.environ.get("BENCH_TP", "1"))
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_req = int(os.environ.get("BENCH_REQUESTS", str(2 * B * R)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        block = int(os.environ.get("BENCH_BLOCK", "64"))
        horizon = int(os.environ.get("BENCH_HORIZON", "64"))
        sys_len = int(os.environ.get("BENCH_SYS_PROMPT", "256"))
        tail_len = int(os.environ.get("BENCH_TAIL", "128"))
        n_tenants = int(os.environ.get("BENCH_TENANTS", str(max(R, 2))))
        n_req = max(n_req, 2 * n_tenants)   # a timed wave must exist
        cap = -(-(sys_len + tail_len + new_tokens) // chunk) * chunk
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        V = cfg.vocab_size
        sys_prompts = [rng.integers(0, V, (sys_len,)).astype(np.int32)
                       for _ in range(n_tenants)]
        tails = [rng.integers(0, V, (tail_len // 2 + int(x),)).astype(
            np.int32) for x in rng.integers(0, tail_len // 2, size=n_req)]
        prompts = [np.concatenate([sys_prompts[i % n_tenants], t])
                   for i, t in enumerate(tails)]

        def build_model():
            # each replica materializes its own weight copy (same seed,
            # identical values) — under BENCH_TP each copy lays out on
            # its OWN replica mesh, which a shared model couldn't
            paddle.seed(0)
            m = LlamaForCausalLM(cfg).bfloat16()
            m.eval()
            return m

        def make_replica(i):
            kw = dict(max_batch=B, max_seq_len=cap, chunk_size=chunk,
                      horizon=horizon, cache_impl="paged",
                      block_size=block, scheduler="fused",
                      enable_prefix_cache=True)
            model = build_model()
            if tp > 1:
                devs = jax.devices()[i * tp:(i + 1) * tp]
                eng = tp_engine(model, tp=tp, devices=devs, **kw)
            else:
                eng = LLMEngine(model, **kw)
            warm = rng.integers(0, V, (3,)).astype(np.int32)
            eng.generate([warm], max_new_tokens=2)
            eng.reset_stats()
            return AsyncLLMServer(eng, max_queue_size=n_req + 1, replica=i)

        def run_cluster(n_replicas, policy):
            replicas = [make_replica(i) for i in range(n_replicas)]
            router = ReplicaRouter(replicas, policy=policy)
            router.start()
            # SEED wave: one request per tenant primes the prefix caches
            # (and, under the affinity policy, spreads the tenants across
            # replicas — the router's outstanding-count load term places
            # simultaneous cold tenants on different replicas). The timed
            # MAIN wave below is the steady state the hit-rate and tok/s
            # numbers describe.
            seed_hs = [router.submit(prompts[i], max_new_tokens=new_tokens,
                                     routing_key=f"tenant{i % n_tenants}")
                       for i in range(n_tenants)]
            seed_outs = [h.result(timeout=1800) for h in seed_hs]
            for srv in replicas:
                srv.engine.reset_stats()
            t0 = time.perf_counter()
            hs = [router.submit(p, max_new_tokens=new_tokens,
                                routing_key=f"tenant{i % n_tenants}")
                  for i, p in enumerate(prompts[n_tenants:],
                                        start=n_tenants)]
            outs = [h.result(timeout=1800) for h in hs]
            wall = time.perf_counter() - t0
            router.stop()
            toks = sum(len(o.token_ids) for o in outs)
            per, hit_tok, pre_tok = [], 0, 0
            for i, srv in enumerate(replicas):
                st = srv.engine.stats
                per.append({
                    "replica": i, "tokens": st["tokens_generated"],
                    "tokens_per_sec": round(
                        st["tokens_generated"] / wall, 1),
                    "prefix_hit_tokens": st["prefix_hit_tokens"],
                    "placements": router.stats["placements"][i]})
                hit_tok += st["prefix_hit_tokens"]
                pre_tok += st["prefill_tokens"]
            return {
                "aggregate_tokens_per_sec": round(toks / wall, 1),
                "per_replica": per,
                "affinity_hit_rate": round(
                    hit_tok / (hit_tok + pre_tok), 4)
                if hit_tok + pre_tok else 0.0,
                "affinity_routed": router.stats["affinity_routed"],
                "resubmitted": router.stats["resubmitted"],
                "wall_s": round(wall, 3),
            }, [list(o.token_ids) for o in seed_outs + outs]

        single, toks_single = run_cluster(1, "affinity")
        cluster, toks_cluster = run_cluster(R, "affinity")
        random_arm, _ = run_cluster(R, "random")
        art_path = os.path.join(_artifact_dir(),
                                "llama_serve_cluster.json")
        with open(art_path, "w") as f:
            json.dump({"single": single, "cluster": cluster,
                       "cluster_random": random_arm}, f, indent=1)
        # r05's single-chip sync-loop serve line (1,158.9 tok/s): the
        # cluster aggregate is comparable only at the captured config on
        # chip — and is an R-replica number, so the ratio is the
        # capacity-scaling claim, not a same-hardware speedup
        at_r05_config = (
            B == 8 and new_tokens == 64 and n_layers == 3
            and hidden == 4096 and ff == hidden * 11 // 4
            and horizon == 64 and chunk == 256 and tp == 1
            and jax.default_backend() != "cpu")
        return {"metric": "llama_serve_cluster_tokens_per_sec",
                "value": cluster["aggregate_tokens_per_sec"],
                "unit": "tokens/s",
                "vs_baseline": (round(
                    cluster["aggregate_tokens_per_sec"] / 1158.9, 4)
                    if at_r05_config else None),
                "replicas": R, "tp": tp, "slots_per_replica": B,
                "single": single, "cluster": cluster,
                "cluster_random": random_arm,
                "cluster_speedup_vs_single": round(
                    cluster["aggregate_tokens_per_sec"]
                    / max(single["aggregate_tokens_per_sec"], 1e-9), 3),
                "affinity_hit_rate": cluster["affinity_hit_rate"],
                "random_hit_rate": random_arm["affinity_hit_rate"],
                # greedy serving: scaling out must not change one token
                "token_parity": toks_single == toks_cluster,
                "requests": n_req, "new_tokens": new_tokens,
                "tenants": n_tenants, "sys_prompt_len": sys_len,
                "chunk": chunk, "block_size": block, "horizon": horizon,
                "telemetry_artifact": art_path}

    if model_name == "llama_serve_lora":
        # Batched multi-LoRA A/B (paddle_tpu/serving/adapters.py): the
        # same base model served (a) WITHOUT an adapter store — the
        # pre-adapter compiled program, the overhead baseline — and (b)
        # with BENCH_ADAPTERS registered adapters and requests round-
        # robining across them through ONE fused paged engine, with an
        # adapter device cache of BENCH_ADAPTER_SLOTS slots (smaller
        # than the adapter count, so LRU swap-ins actually happen and
        # the swap rate is a real number). A per-adapter greedy PARITY
        # probe runs each adapter's stream against an offline
        # merged-weights reference engine.
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import (AsyncLLMServer, AdapterStore,
                                        apply_merged, random_lora_weights)
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_req = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        block = int(os.environ.get("BENCH_BLOCK", "64"))
        prompt_len = int(os.environ.get("BENCH_PROMPT", "128"))
        n_adapters = int(os.environ.get("BENCH_ADAPTERS", "8"))
        n_slots = int(os.environ.get("BENCH_ADAPTER_SLOTS", "4"))
        rank = int(os.environ.get("BENCH_RANK", "8"))
        n_parity = int(os.environ.get("BENCH_PARITY_ADAPTERS", "2"))
        cap = -(-(prompt_len + new_tokens) // chunk) * chunk
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        V = cfg.vocab_size
        prompts = [rng.integers(0, V, (prompt_len,)).astype(np.int32)
                   for _ in range(n_req)]
        store = AdapterStore(cfg, rank=rank)
        aids = [store.register(
            random_lora_weights(cfg, rank=rank, seed=100 + i, scale=0.02),
            alpha=2.0) for i in range(n_adapters)]

        def build_model():
            paddle.seed(0)
            m = LlamaForCausalLM(cfg).bfloat16()
            m.eval()
            return m

        def run_arm(adapter_ids, use_store):
            eng = LLMEngine(build_model(), max_batch=B, max_seq_len=cap,
                            chunk_size=chunk, cache_impl="paged",
                            block_size=block, scheduler="fused",
                            adapter_store=store if use_store else None,
                            adapter_cache_slots=n_slots)
            warm = rng.integers(0, V, (3,)).astype(np.int32)
            eng.generate([warm], max_new_tokens=2)
            eng.reset_stats()
            server = AsyncLLMServer(eng, max_queue_size=n_req + 1)
            server.start()
            t0 = time.perf_counter()
            hs = [server.submit(p, max_new_tokens=new_tokens,
                                adapter_id=aid)
                  for p, aid in zip(prompts, adapter_ids)]
            outs = [h.result(timeout=1800) for h in hs]
            wall = time.perf_counter() - t0
            server.stop()
            toks = sum(len(o.token_ids) for o in outs)
            st = eng.stats
            return {
                "tokens_per_sec": round(toks / wall, 1),
                "adapter_swaps": int(st["adapter_swaps"]),
                "adapter_cache_hits": int(st["adapter_cache_hits"]),
                "swap_rate": round(st["adapter_swaps"] / max(n_req, 1), 4),
                "wall_s": round(wall, 3),
            }

        base = run_arm([0] * n_req, use_store=False)
        mix = run_arm([aids[i % n_adapters] for i in range(n_req)],
                      use_store=True)
        # per-adapter greedy parity probe vs merged-weights references
        parity = True
        probe = prompts[0][:32]
        eng = LLMEngine(build_model(), max_batch=2, max_seq_len=cap,
                        chunk_size=chunk, cache_impl="paged",
                        block_size=block, scheduler="fused",
                        adapter_store=store, adapter_cache_slots=n_slots)
        for aid in aids[:n_parity]:
            rid = eng.add_request(probe, max_new_tokens=16, adapter_id=aid)
            while eng.has_unfinished():
                eng.step()
            got = eng.finished_outputs.pop(rid).token_ids
            merged = build_model()
            apply_merged(merged, store, aid)
            ref_eng = LLMEngine(merged, max_batch=2, max_seq_len=cap,
                                chunk_size=chunk, cache_impl="paged",
                                block_size=block, scheduler="fused")
            (ref,) = ref_eng.generate([probe], max_new_tokens=16)
            parity = parity and (got == ref.token_ids)
        return {"metric": "llama_serve_lora_tokens_per_sec",
                "value": mix["tokens_per_sec"],
                "unit": "tokens/s", "vs_baseline": None,
                "base": base, "adapter_mix": mix,
                "lora_overhead_pct": round(
                    (1.0 - mix["tokens_per_sec"]
                     / max(base["tokens_per_sec"], 1e-9)) * 100, 2),
                "swap_rate": mix["swap_rate"],
                "token_parity_vs_merged": parity,
                "adapters": n_adapters, "adapter_cache_slots": n_slots,
                "rank": rank, "requests": n_req, "slots": B,
                "new_tokens": new_tokens, "prompt_len": prompt_len,
                "chunk": chunk, "block_size": block}

    if model_name == "llama_serve_embed":
        # Mixed generate + PREFILL-ONLY embedding serving through one
        # fused engine (the multi-tenant scenario-diversity rung): a
        # generate-only arm is the control, then the same generate
        # workload re-runs with BENCH_EMBED embedding requests riding
        # the SAME token-budget walk — the mixed arm reports generation
        # tok/s (interference cost) plus embeds/s (the new capacity).
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference import LLMEngine
        from paddle_tpu.serving import AsyncLLMServer
        B = int(os.environ.get("BENCH_BATCH", "8"))
        new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "64"))
        n_gen = int(os.environ.get("BENCH_REQUESTS", str(2 * B)))
        n_emb = int(os.environ.get("BENCH_EMBED", str(n_gen)))
        n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
        hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
        ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
        heads = max(hidden // 128, 1)
        chunk = int(os.environ.get("BENCH_CHUNK", "256"))
        block = int(os.environ.get("BENCH_BLOCK", "64"))
        prompt_len = int(os.environ.get("BENCH_PROMPT", "128"))
        emb_len = int(os.environ.get("BENCH_EMBED_LEN", "256"))
        cap = -(-(max(prompt_len, emb_len) + new_tokens) // chunk) * chunk
        cfg = LlamaConfig(vocab_size=32000, hidden_size=hidden,
                          intermediate_size=ff, num_hidden_layers=n_layers,
                          num_attention_heads=heads,
                          num_key_value_heads=heads,
                          max_position_embeddings=cap)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg).bfloat16()
        model.eval()
        V = cfg.vocab_size
        gen_prompts = [rng.integers(0, V, (prompt_len,)).astype(np.int32)
                       for _ in range(n_gen)]
        emb_prompts = [rng.integers(0, V, (emb_len,)).astype(np.int32)
                       for _ in range(n_emb)]

        def run_arm(with_embed):
            eng = LLMEngine(model, max_batch=B, max_seq_len=cap,
                            chunk_size=chunk, cache_impl="paged",
                            block_size=block, scheduler="fused")
            warm = rng.integers(0, V, (3,)).astype(np.int32)
            eng.generate([warm], max_new_tokens=2)
            eng.reset_stats()
            server = AsyncLLMServer(
                eng, max_queue_size=n_gen + n_emb + 1)
            server.start()
            t0 = time.perf_counter()
            hs = [server.submit(p, max_new_tokens=new_tokens)
                  for p in gen_prompts]
            ehs = [server.submit_embed(p)
                   for p in emb_prompts] if with_embed else []
            outs = [h.result(timeout=1800) for h in hs]
            eouts = [h.result(timeout=1800) for h in ehs]
            wall = time.perf_counter() - t0
            server.stop()
            toks = sum(len(o.token_ids) for o in outs)
            assert all(o.embedding is not None for o in eouts)
            snap = server.telemetry.snapshot(wall_s=wall)
            return {
                "tokens_per_sec": round(toks / wall, 1),
                "embeds_per_sec": round(len(eouts) / wall, 2)
                if with_embed else 0.0,
                "embed_tokens_per_sec": round(
                    sum(len(p) for p in emb_prompts) / wall, 1)
                if with_embed else 0.0,
                "ttft_p50_ms": round(
                    snap["latency"]["ttft"]["p50_s"] * 1e3, 1),
                "wall_s": round(wall, 3),
            }, [list(o.token_ids) for o in outs]

        gen_only, toks_only = run_arm(False)
        mixed, toks_mixed = run_arm(True)
        return {"metric": "llama_serve_embed_mixed_tokens_per_sec",
                "value": mixed["tokens_per_sec"],
                "unit": "tokens/s", "vs_baseline": None,
                "generate_only": gen_only, "mixed": mixed,
                "embeds_per_sec": mixed["embeds_per_sec"],
                "generate_interference_pct": round(
                    (1.0 - mixed["tokens_per_sec"]
                     / max(gen_only["tokens_per_sec"], 1e-9)) * 100, 2),
                # greedy serving: embed traffic riding the same steps
                # must not change one generated token
                "token_parity": toks_only == toks_mixed,
                "gen_requests": n_gen, "embed_requests": n_emb,
                "slots": B, "new_tokens": new_tokens,
                "prompt_len": prompt_len, "embed_len": emb_len,
                "chunk": chunk, "block_size": block}

    if model_name == "conv_roofline":
        return _bench_conv_roofline()

    if model_name == "dispatch":
        return _bench_dispatch()

    if model_name == "memcheck":
        return _bench_memcheck()

    if model_name == "loss_parity":
        return _bench_loss_parity()

    raise ValueError(f"unknown BENCH_MODEL {model_name!r}")


def run_loss_parity(cfg_over=None, B=4, S=1024, steps=100, lr=3e-4):
    """Long-horizon loss-curve parity (VERDICT r3 #8): train the SAME llama
    config twice — bf16 params with fp32 AdamW masters (the production
    chain) vs an all-fp32 reference — with matched data order and RNG, and
    return the two trajectories + max relative divergence. Shared by the
    on-chip bench mode and the CPU CI test (tests/test_loss_parity.py)."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    base = dict(vocab_size=8192, hidden_size=2048, intermediate_size=5632,
                num_hidden_layers=2, num_attention_heads=16,
                num_key_value_heads=16, max_position_embeddings=S,
                use_recompute=True)
    base.update(cfg_over or {})
    cfg = LlamaConfig(**base)

    def run(bf16):
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        if bf16:
            model = model.bfloat16()
        optimizer = opt.AdamW(learning_rate=lr,
                              parameters=model.parameters(),
                              weight_decay=0.01, multi_precision=bf16)

        def loss_fn(m, ids, labels):
            loss, _ = m(ids, labels=labels)
            return loss

        step = TrainStep(model, loss_fn, optimizer, donate=True)
        rng = np.random.default_rng(42)  # matched data order across runs
        losses = []
        for _ in range(steps):
            ids = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (B, S)), dtype="int32")
            labels = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (B, S)), dtype="int32")
            losses.append(float(np.asarray(step(ids, labels)._value)))
        return losses

    bf16 = run(True)
    ref = run(False)
    rel = [abs(a - b) / max(abs(b), 1e-9) for a, b in zip(bf16, ref)]
    return {"bf16": bf16, "fp32": ref,
            "max_rel_divergence": max(rel),
            "final_rel_divergence": rel[-1],
            "steps": steps}


def _bench_loss_parity():
    steps = int(os.environ.get("BENCH_PARITY_STEPS", "100"))
    B = int(os.environ.get("BENCH_BATCH", "4"))
    S = int(os.environ.get("BENCH_SEQ", "1024"))
    res = run_loss_parity(B=B, S=S, steps=steps)
    return {"metric": "llama_bf16_vs_fp32_loss_divergence_100step",
            "value": round(res["max_rel_divergence"] * 100, 3),
            "unit": "% max rel", "vs_baseline": None,
            "final_rel_pct": round(res["final_rel_divergence"] * 100, 3),
            "steps": steps,
            "loss_first_bf16": round(res["bf16"][0], 4),
            "loss_last_bf16": round(res["bf16"][-1], 4),
            "loss_last_fp32": round(res["fp32"][-1], 4)}


def _bench_memcheck():
    """Cross-validate the 7B-fit memory model against the REAL TPU compiler
    (VERDICT r3 weak #4/#5): AOT-compile the flagship bench config on this
    backend and compare predicted residency (compiled state bytes + the
    trace-level saved-residuals model that the virtual-mesh proofs rest on)
    with the compiler's own ``peak_memory_in_bytes``. The gap IS the
    in-segment transient — the number the 7B proof's "tens of MB" claim
    needs. Compile-only: no arrays are materialized."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.utils.memory_model import residual_bytes

    B = int(os.environ.get("BENCH_BATCH", "6"))
    S = int(os.environ.get("BENCH_SEQ", "2048"))
    n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
    hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
    ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
    heads = max(hidden // 128, 1)
    set_flags({"adamw_bf16_moments": True, "use_fused_adamw": False})
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=hidden, intermediate_size=ff,
        num_hidden_layers=n_layers, num_attention_heads=heads,
        num_key_value_heads=heads, max_position_embeddings=S,
        use_recompute=True)
    paddle.seed(0)
    with paddle.LazyGuard():
        model = LlamaForCausalLM(cfg).bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    optimizer = opt.AdamW(learning_rate=3e-4, parameters=model.parameters(),
                          weight_decay=0.01, multi_precision=True)

    def loss_fn(m, ids, labels):
        loss, _ = m(ids, labels=labels)
        return loss

    step = TrainStep(model, loss_fn, optimizer, donate=True)
    ids = Tensor(jax.ShapeDtypeStruct((B, S), jnp.int32))
    compiled = step.aot_compile(ids, ids)
    m = compiled.memory_analysis()
    state = int(m.argument_size_in_bytes)
    peak = int(getattr(m, "peak_memory_in_bytes", 0))
    try:
        residuals = residual_bytes(step, (ids, ids), seq_len=S)
        resid_err = None
    except RuntimeError as e:
        residuals, resid_err = None, str(e)
    out = {"metric": "memcheck_7b_model_vs_compiler",
           "value": None, "unit": "pct", "vs_baseline": None,
           "params": n_params,
           "state_bytes_compiled": state,
           "residual_bytes_predicted": residuals,
           "peak_bytes_compiler": peak,
           "temp_bytes_compiler": int(getattr(m, "temp_size_in_bytes", 0)),
           "backend": jax.default_backend()}
    if residuals is not None and peak:
        predicted = state + residuals
        out["predicted_resident_bytes"] = predicted
        out["transient_bytes"] = peak - predicted
        out["value"] = round((peak - predicted) / peak * 100, 2)
    if resid_err:
        out["residual_model_error"] = resid_err[:200]
    return out


def _measured_stream_bw():
    """Measured HBM stream bandwidth (bytes/s) from the DEVICE-track
    duration of a large bf16 axpy fusion — the roofline denominator.
    Host-side timing has a ~1 ms dispatch floor through the axon tunnel;
    the profiler's device track does not."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.utils.roofline import profile_device_events

    N = 128 * 1024 * 1024  # 256 MB per array
    x = jnp.ones((N,), jnp.bfloat16)
    y = jnp.ones((N,), jnp.bfloat16)
    axpy = jax.jit(lambda x, y: x * jnp.bfloat16(1.0001) + y)
    r = axpy(x, y)
    float(np.asarray(r[0]))

    def run(steps):
        for _ in range(steps):
            r = axpy(x, y)
        float(np.asarray(r[0]))

    ev, _ = profile_device_events(run, steps=8)
    # the only compute event is the axpy loop fusion: 2 reads + 1 write
    name, best = None, 0.0
    for n, d in ev.items():
        if d["total_us"] > best and not n.startswith("copy"):
            name, best = n, d["total_us"]
    per_step = best / 8 / 1e6
    return 3 * N * 2 / per_step


def _bench_conv_roofline():
    """Regenerate docs/artifacts/conv_roofline_proof.json (VERDICT r4 #1):
    per-fusion achieved FLOP/s + B/s vs each fusion's own roofline bound,
    for the resnet50 and unet bench steps, on the real chip. The reference
    counterpart is the cudnn conv stack with layout/algorithm autotuning
    (paddle/phi/kernels/gpudnn/conv_kernel.cu,
    phi/kernels/autotune/auto_tune_base.h); here the question "is XLA's
    conv lowering at the hardware ceiling" is answered per fusion."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.utils.roofline import (profile_device_events,
                                           roofline_table)

    steps = int(os.environ.get("BENCH_STEPS", "4"))
    rng = np.random.default_rng(0)
    peak = _peak_flops(jax.devices()[0])
    bw = _measured_stream_bw()
    models = {}

    def analyze(name, step, args):
        compiled = step.aot_compile(*args)
        hlo = compiled.as_text()
        for _ in range(2):  # donated-layout recompile must precede trace
            loss = step(*args)
        float(np.asarray(loss._value))

        def run(n):
            for _ in range(n):
                loss = step(*args)
            float(np.asarray(loss._value))

        ev, jit_total = profile_device_events(run, steps=steps)
        # self-calibrate the bandwidth roofline: the HIGHEST sustained HBM
        # rate demonstrated by any long-running fusion of this very step
        # (or the axpy probe) — the most self-critical denominator
        rows, _ = roofline_table(hlo, ev, steps, peak, bw)
        # capped at the chip's spec bandwidth: a fusion "demonstrating" more
        # than spec means residual byte overcount (aliased operands), not a
        # faster memory system
        bw_cal = min(max([bw] + [r["achieved_gbs"] * 1e9 for r in rows
                                 if r["time_us"] > 200
                                 and r["bytes"] > 32e6]),
                     819e9)
        rows, unmatched = roofline_table(hlo, ev, steps, peak, bw_cal)
        # module container events give the true device step time; leaf
        # events + unmatched is the fallback
        step_us = (jit_total / steps if jit_total
                   else sum(r["time_us"] for r in rows) + unmatched)
        conv = [r for r in rows if r["kind"] == "conv"]
        conv_us = sum(r["time_us"] for r in conv)
        conv_bound = sum(r["bound_us"] for r in conv)
        # "major" fusions: >=2% of step device time each
        major = [r for r in conv if r["time_us"] >= 0.02 * step_us]
        tot_bytes = sum(r["bytes"] for r in rows)
        tot_flops = sum(r["flops"] for r in rows)
        step_bound_us = max(tot_bytes / bw_cal, tot_flops / peak) * 1e6
        models[name] = {
            "step_device_us": round(step_us, 1),
            "hbm_bw_roofline_gbs": round(bw_cal / 1e9, 1),
            "total_hbm_gb_per_step": round(tot_bytes / 1e9, 2),
            "total_tflop_per_step": round(tot_flops / 1e12, 3),
            "aggregate_gbs": round(tot_bytes / step_us / 1e3, 1),
            "achieved_pct_of_peak_flops": round(
                tot_flops / (step_us / 1e6) / peak * 100, 2),
            # the whole step against ITS OWN roofline: the bound the
            # reference's tuned conv stack would also be subject to
            "step_bound_us": round(step_bound_us, 1),
            "step_roofline_eff": round(step_bound_us / step_us, 3),
            "step_bound_by": ("compute" if tot_flops / peak
                              >= tot_bytes / bw_cal else "memory"),
            "conv_time_share": round(conv_us / step_us, 3),
            "conv_weighted_roofline_eff": round(conv_bound / conv_us, 3),
            "major_conv_fusions": len(major),
            "major_conv_fusions_above_80pct": sum(
                1 for r in major if (r["roofline_eff"] or 0) >= 0.8),
            "unmatched_us_per_step": round(unmatched, 1),
            "rows": rows[:40],
        }

    # resnet50, exactly the bench config
    B = int(os.environ.get("BENCH_BATCH", "128"))
    paddle.seed(0)
    from paddle_tpu.vision.models import resnet50
    model = resnet50(num_classes=1000, data_format="NHWC").bfloat16()
    optimizer = opt.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    step = TrainStep(model, lambda m, x, y: F.cross_entropy(m(x), y),
                     optimizer)
    x = paddle.to_tensor(rng.standard_normal(
        (B, 224, 224, 3)).astype(np.float32)).astype("bfloat16")
    y = paddle.to_tensor(rng.integers(0, 1000, B))
    analyze("resnet50", step, (x, y))
    del model, optimizer, step, x, y
    import gc
    gc.collect()

    # unet, exactly the bench config
    from paddle_tpu.models import UNetConfig, UNetModel, diffusion_loss
    Bu = int(os.environ.get("BENCH_UNET_BATCH", "4"))
    paddle.seed(0)
    um = UNetModel(UNetConfig.sd_unet(use_recompute=True)).bfloat16()
    uopt = opt.AdamW(learning_rate=1e-4, parameters=um.parameters(),
                     multi_precision=True)
    alphas = paddle.to_tensor(np.linspace(0.999, 0.01, 1000)
                              .astype(np.float32))
    ustep = TrainStep(um, lambda m, lat, t, ctx, noise: diffusion_loss(
        m, lat, t, ctx, noise, alphas), uopt)
    lat = paddle.to_tensor(rng.standard_normal(
        (Bu, 64, 64, 4)).astype(np.float32)).astype("bfloat16")
    t = paddle.to_tensor(rng.integers(0, 1000, Bu))
    ctx = paddle.to_tensor(rng.standard_normal(
        (Bu, 77, 768)).astype(np.float32)).astype("bfloat16")
    noise = paddle.to_tensor(rng.standard_normal(
        (Bu, 64, 64, 4)).astype(np.float32)).astype("bfloat16")
    analyze("unet", ustep, (lat, t, ctx, noise))

    artifact = {
        "description": "Per-fusion roofline proof for the conv workloads "
                       "(resnet50 B=128, sd-unet B=4 train steps). "
                       "bound_us = max(flops/peak, bytes/bw); "
                       "roofline_eff = bound_us/time_us (1.0 = at the "
                       "roofline). flops are VALID-pair conv MACs x2 "
                       "(padding/dilation zeros excluded); bytes exclude "
                       "VMEM-prefetched (S(1)) operands. bw is "
                       "self-calibrated per model: max sustained HBM rate "
                       "demonstrated by any fusion of the same step.",
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "peak_bf16_flops": peak,
        "hbm_bw_axpy_probe_gbs": round(bw / 1e9, 1),
        "models": models,
        "attempt_ladder": [
            {"experiment": "layout NCHW vs NHWC end-to-end",
             "result": "EQUAL full-step throughput (XLA layout-assigns "
                       "convs; isolated microbenches misleadingly show "
                       "NHWC 1.5x)", "recorded": "round 3, PROGRESS + "
                       "BENCH_LAYOUT=NCHW knob in bench.py"},
            {"experiment": "resnet batch sweep B=128 vs 256",
             "result": "no change in imgs/s/chip — bandwidth-bound, "
                       "bigger batch scales bytes with flops",
             "recorded": "round 3"},
            {"experiment": "unet batch B=4 vs B=8",
             "result": "15.1 vs 15.2% MFU — batch-insensitive",
             "recorded": "round 4, PROGRESS unet_mfu_measured"},
            {"experiment": "FLOP accounting audit (this artifact)",
             "result": "bench.py used 4.1 GMACs/img as FLOPs — true "
                       "fwd is ~8.2 GFLOP/img (per-instruction HLO "
                       "count); resnet MFU restated ~2x higher",
             "recorded": "round 5, this file"},
            {"experiment": "unet attention: Pallas flash vs XLA einsum "
                           "A/B at every sd-unet shape (fwd+bwd, device-"
                           "track timed)",
             "result": "flash wins 2.6-20x everywhere: self 4096/d40 "
                       "5.06ms (einsum OOMs: 2GB logits buffers), cross "
                       "4096/77 0.73 vs 2.67ms, self 1024/d80 0.39 vs "
                       "8.60ms, cross 1024/77 0.14 vs 0.60ms, self 256/"
                       "d160 0.07 vs 0.45ms, cross 256/77 0.06 vs 0.15ms. "
                       "The 4096/d40 kernel runs AT the lane-padded MXU "
                       "bound (~4.8ms ideal for d=40 padded to 128 lanes) "
                       "— the 3.2x padding waste is inherent to head_dim "
                       "40 on a 128x128 systolic array, an SD architecture "
                       "choice, not a kernel deficiency",
             "recorded": "round 5, this file"},
            {"experiment": "per-fusion roofline (this artifact)",
             "result": "see models.*: conv fusions are MEMORY-bound on "
                       "resnet (weighted eff vs own bound in "
                       "conv_weighted_roofline_eff); the step as a whole "
                       "runs at step_roofline_eff of its bandwidth bound",
             "recorded": "round 5, this file"},
        ],
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "docs", "artifacts", "conv_roofline_proof.json")
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    return {"metric": "conv_roofline_weighted_eff",
            "value": models["resnet50"]["conv_weighted_roofline_eff"],
            "unit": "x of roofline", "vs_baseline": None,
            "unet_eff": models["unet"]["conv_weighted_roofline_eff"],
            "hbm_bw_measured_gbs": round(bw / 1e9, 1),
            "artifact": path}


def _bench_dispatch():
    """Eager op-dispatch microbenchmark (reference: the codegen'd allocation-
    free eager path, fluid/eager/auto_code_generator/generator/eager_gen.py).
    Measures forward ops/sec for small add/matmul/layer_norm with the
    compiled dispatch cache on vs off (grad recording enabled, so the cached
    path includes building the jitted vjp pair)."""
    import time
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.core import tensor as T

    paddle.seed(0)
    x = paddle.randn([128, 128])
    x.stop_gradient = False
    y = paddle.randn([128, 128])
    w = paddle.randn([128])
    b = paddle.randn([128])

    import jax.numpy as jnp
    xv, yv, wv, bv = x._value, y._value, w._value, b._value
    jadd = jax.jit(lambda a, b2: a + b2)
    jmm = jax.jit(jnp.matmul)

    def jln(a, weight, bias):
        mu = jnp.mean(a, -1, keepdims=True)
        var = jnp.var(a, -1, keepdims=True)
        return (a - mu) / jnp.sqrt(var + 1e-5) * weight + bias

    jln = jax.jit(jln)

    cases = {
        "add": (lambda: x + y, lambda: jadd(xv, yv)),
        "matmul": (lambda: paddle.matmul(x, y), lambda: jmm(xv, yv)),
        "layer_norm": (lambda: F.layer_norm(x, [128], weight=w, bias=b),
                       lambda: jln(xv, wv, bv)),
    }

    def rate(f, n=300):
        f(); f()
        t0 = time.perf_counter()
        for _ in range(n):
            out = f()
        jax.block_until_ready(getattr(out, "_value", out))
        return n / (time.perf_counter() - t0)

    result = {}
    saved_max = T._DISPATCH_CACHE_MAX
    for label, (f, raw) in cases.items():
        T._DISPATCH_CACHE_MAX = saved_max
        fast = rate(f)
        T._DISPATCH_CACHE.clear()
        T._DISPATCH_CACHE_MAX = 0   # force the uncached path
        slow = rate(f, n=60)
        T._DISPATCH_CACHE_MAX = saved_max
        # absolute target: a pre-jitted raw-jax dispatch of the same compute
        # (no tape, no Tensor wrapper) — the residual overhead is tracked
        raw_rate = rate(raw)
        result[label] = {"cached_ops_per_sec": round(fast, 1),
                         "uncached_ops_per_sec": round(slow, 1),
                         "raw_jax_ops_per_sec": round(raw_rate, 1),
                         "speedup": round(fast / slow, 2),
                         "overhead_vs_raw_jax": round(raw_rate / fast, 2)}

    gmean = float(np.prod([v["speedup"] for v in result.values()])) ** (
        1.0 / len(result))
    over = float(np.prod([v["overhead_vs_raw_jax"]
                          for v in result.values()])) ** (1.0 / len(result))
    return {"metric": "eager_dispatch_speedup_geomean",
            "value": round(gmean, 2), "unit": "x", "vs_baseline": None,
            "overhead_vs_raw_jax_geomean": round(over, 2),
            "detail": result}


def _emit_analysis_header():
    """One JSON header line before the workload ladder: the static-
    analysis state of the tree (paddle_tpu.analysis) so the trajectory
    records the baseline burn-down next to the perf numbers.
    ``analysis_findings`` = active (would-fail) findings — 0 on a clean
    tree; ``analysis_baselined`` = grandfathered debt still to burn."""
    try:
        from paddle_tpu.analysis import count_findings
        here = os.path.dirname(os.path.abspath(__file__))
        active, baselined, suppressed = count_findings(
            [os.path.join(here, "paddle_tpu")],
            baseline_path=os.path.join(here, "analysis_baseline.json"))
        print(json.dumps({
            "metric": "analysis_findings", "value": active, "unit":
            "findings", "vs_baseline": None,
            "analysis_baselined": baselined,
            "analysis_suppressed": suppressed}), flush=True)
    except Exception as e:       # the bench ladder must not die on lint
        print(json.dumps({"metric": "analysis_findings", "value": None,
                          "unit": "findings", "vs_baseline": None,
                          "error": f"{type(e).__name__}: {e}"}),
              flush=True)


def _run_all():
    """Default driver mode: one JSON line per BASELINE config (1-5) plus
    llama_decode, with the flagship llama LAST so single-line tail parsing
    keeps working. Each config runs in its own subprocess — flag settings
    and HBM stay isolated, and one config failing doesn't take down the
    rest."""
    import subprocess
    import sys
    _emit_analysis_header()
    # the int8/int4 rungs re-baseline the weight-only-quantized decode
    # ratios IN the ladder (same two-length-differential harness, same
    # subprocess isolation) — the 1.35x/1.67x numbers ROUND5_NOTES
    # flagged "pending re-baseline" regenerate here on every `all` run
    # instead of being re-quoted (compare their tokens/s against the
    # bf16 llama_decode line; each JSON line carries weight_dtype).
    for name, extra in [
            ("resnet50", None), ("bert", None), ("vit", None),
            ("unet", None), ("llama_decode", None),
            ("llama_decode_int8",
             {"BENCH_MODEL": "llama_decode", "BENCH_WEIGHT_DTYPE": "int8"}),
            ("llama_decode_int4",
             {"BENCH_MODEL": "llama_decode", "BENCH_WEIGHT_DTYPE": "int4"}),
            ("llama_paged_decode", None), ("llama_serve", None),
            ("llama_serve_fused", None), ("llama_serve_prefix_cache", None),
            ("llama_serve_kv_quant", None),
            ("llama_serve_kv_tier", None),
            ("llama_serve_disagg", None),
            ("llama_serve_slo", None),
            ("llama_serve_cluster", None), ("llama_serve_spec", None),
            ("llama_serve_lora", None), ("llama_serve_embed", None),
            ("llama", None)]:
        env = dict(os.environ, BENCH_MODEL=name)
        if extra:
            env.update(extra)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=1800)
            line = next((ln for ln in reversed(proc.stdout.splitlines())
                         if ln.startswith("{")), None)
            err = proc.stderr[-400:]
        except subprocess.TimeoutExpired:
            line, err = None, "timeout after 1800s"
        if line:
            print(line, flush=True)
        else:
            print(json.dumps({"metric": f"{name}_bench_failed", "value": None,
                              "unit": "", "vs_baseline": None, "error": err}),
                  flush=True)


def main():
    model_name = os.environ.get("BENCH_MODEL", "all")
    if model_name == "all":
        _run_all()
        return

    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit.api import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if model_name != "llama":
        out = _bench_other(model_name)
        out["device"] = getattr(jax.devices()[0], "device_kind", "unknown")
        print(json.dumps(out))
        return

    # defaults = best measured config at representative depth (>=3 of the
    # 7B-wide d=4096/ff=11264 decoder layers) and the 2k llama pretrain
    # context. Per-layer remat + flash attention lets B=6 fit beside the
    # 12.3GB of AdamW state for 879M params; the bigger batch amortizes the
    # optimizer/master-weight HBM traffic (the measured dominant overhead).
    # 24-step curve (2026-07-30): L3B6+remat 55.7%, L3B3+remat 53.4,
    # L3B8+remat 53.2, L2B3 no-remat 55.3 (old default), L3B12/L4 OOM
    # (L4 AdamW state alone is 15.2G of the 15.75G HBM).
    B = int(os.environ.get("BENCH_BATCH", "6"))
    S = int(os.environ.get("BENCH_SEQ", "2048"))
    n_layers = int(os.environ.get("BENCH_LAYERS", "3"))
    steps = int(os.environ.get("BENCH_STEPS", "12"))
    # bf16 moment storage (fp32 update math): -3.5GB optimizer HBM traffic,
    # measured +0.9 MFU at the default config (56.6 vs 55.7). Framework
    # default stays fp32 (reference-exact trajectories); the bench opts in
    # and reports the choice in its JSON line.
    bf16_moments = os.environ.get("BENCH_BF16_MOMENTS", "1") == "1"
    if bf16_moments:
        from paddle_tpu.core.flags import set_flags
        set_flags({"adamw_bf16_moments": True})
    hidden = int(os.environ.get("BENCH_HIDDEN", "4096"))
    ff = int(os.environ.get("BENCH_FF", str(hidden * 11 // 4)))
    heads = max(hidden // 128, 1)

    fused = os.environ.get("BENCH_FUSED", "0") == "1"
    remat = os.environ.get("BENCH_REMAT", "1") == "1"
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=hidden, intermediate_size=ff,
        num_hidden_layers=n_layers, num_attention_heads=heads,
        num_key_value_heads=heads, max_position_embeddings=S,
        fuse_attention_qkv=fused, fuse_swiglu=fused,
        use_recompute=remat,
    )
    paddle.seed(0)
    model = LlamaForCausalLM(cfg).bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())

    bench_opt = os.environ.get("BENCH_OPT", "adamw")
    if bench_opt == "sgd":
        optimizer = opt.SGD(learning_rate=3e-4, parameters=model.parameters(),
                            multi_precision=False)
    elif bench_opt == "adamw_sr":
        # master-weight-FREE AdamW: bf16 params + moments + in-kernel
        # stochastic rounding — 6 B/param of optimizer state (vs 14 with
        # masters). Measured: throughput TIES the master chain on this chip
        # (optimizer traffic is latency-hidden); the win is the ~6.7 GB of
        # freed HBM at 7B scale (see tests/test_7b_scale.py SR footprint)
        from paddle_tpu.core.flags import set_flags
        set_flags({"adamw_stochastic_rounding": True,
                   "adamw_bf16_moments": True})
        optimizer = opt.AdamW(learning_rate=3e-4,
                              parameters=model.parameters(),
                              weight_decay=0.01, multi_precision=False)
    else:
        optimizer = opt.AdamW(learning_rate=3e-4,
                              parameters=model.parameters(),
                              weight_decay=0.01, multi_precision=True)

    def loss_fn(m, ids, labels):
        loss, _ = m(ids, labels=labels)
        return loss

    accum = int(os.environ.get("BENCH_ACCUM", "1"))
    step = TrainStep(model, loss_fn, optimizer, accumulate_steps=accum)

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, size=(B, S)), dtype="int32")
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, size=(B, S)), dtype="int32")

    # warmup / compile TWO full accumulation cycles (sync via scalar host
    # fetch: the tunnel's block_until_ready is a no-op). Two, not one: paths
    # whose first call returns donated outputs in a different layout (e.g.
    # pallas-written params) trigger a one-time recompile on the SECOND
    # call, which must not land inside the d1 timing window.
    for _ in range(2 * accum):
        loss = step(ids, labels)
    final_loss = float(np.asarray(loss._value))

    # differential timing cancels the dispatch+fetch round-trip latency;
    # timed units are whole accumulation cycles so update cost amortizes
    t0 = time.perf_counter()
    for _ in range(accum):
        loss = step(ids, labels)
    np.asarray(loss._value)
    d1 = time.perf_counter() - t0

    cycles = max(steps // accum, 1)
    t0 = time.perf_counter()
    for _ in range((cycles + 1) * accum):
        loss = step(ids, labels)
    final_loss = float(np.asarray(loss._value))
    dn = time.perf_counter() - t0

    if os.environ.get("BENCH_DEBUG"):
        import sys
        print(f"[bench debug] d1={d1:.3f}s dn={dn:.3f}s cycles={cycles}",
              file=sys.stderr)
    dt = max(dn - d1, 1e-9)
    tokens_per_sec = cycles * accum * B * S / dt
    flops_per_token = model.flops_per_token(S)
    peak = _peak_flops(jax.devices()[0])
    mfu = flops_per_token * tokens_per_sec / peak

    print(json.dumps({
        "metric": "llama_1chip_train_mfu",
        "value": round(mfu * 100, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu / 0.45, 4),
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "step_time_s": round(dt / steps, 4),
        "params": n_params,
        "loss": final_loss,
        "bf16_moments": bf16_moments,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
    }))


if __name__ == "__main__":
    main()
