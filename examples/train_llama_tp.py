"""Llama training with tensor parallelism on a device mesh — the north-star
config shape (BASELINE config 3) at toy size.

Single process over all visible devices:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/train_llama_tp.py
Multi-process: python -m paddle_tpu.distributed.launch --nproc_per_node=N \
      examples/train_llama_tp.py
"""
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.jit.api import TrainStep
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.llama import llama_tp_spec


def main():
    n = len(jax.devices())
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=16 * n, hidden_size=8 * n,
                      intermediate_size=16 * n, num_hidden_layers=2,
                      num_attention_heads=n, num_key_value_heads=n,
                      max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    mesh = Mesh(np.array(jax.devices()), ("mp",))
    for name, p in model.named_parameters():
        p._value = jax.device_put(p._value,
                                  NamedSharding(mesh, llama_tp_spec(name)))

    optimizer = opt.AdamW(learning_rate=3e-4,
                          parameters=model.parameters(),
                          multi_precision=True)
    step = TrainStep(model, lambda m, ids, lbl: m(ids, labels=lbl)[0],
                     optimizer)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, (4, 32)),
                           dtype="int32")
    for i in range(10):
        loss = step(ids, ids)
        if i % 3 == 0 or i == 9:
            print(f"step {i}: loss {float(loss.numpy()):.4f} "
                  f"(TP={n})", flush=True)


if __name__ == "__main__":
    main()
