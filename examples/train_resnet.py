"""Train ResNet-50 on synthetic data — BASELINE config 1 shape.

Run: python examples/train_resnet.py [--batch 128] [--steps 20]
(On a machine without a TPU it runs on CPU; pass --tiny for a smoke run.)
"""
import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.jit.api import TrainStep
from paddle_tpu.vision.models import resnet18, resnet50


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--tiny", action="store_true")
    args = ap.parse_args()

    paddle.seed(0)
    model = (resnet18 if args.tiny else resnet50)(
        num_classes=1000, data_format="NHWC").bfloat16()
    optimizer = opt.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    step = TrainStep(model, lambda m, x, y: F.cross_entropy(m(x), y),
                     optimizer)

    side = 64 if args.tiny else 224
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal(
        (args.batch, side, side, 3)).astype(np.float32)).astype("bfloat16")
    y = paddle.to_tensor(rng.integers(0, 1000, args.batch))
    for i in range(args.steps):
        loss = step(x, y)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss.numpy()):.4f}", flush=True)


if __name__ == "__main__":
    main()
