"""Serve a llama-family model through the async serving subsystem.

Run: python examples/serve_llama.py          # tiny demo model, mixed requests
Shows: the AsyncLLMServer front (pipelined background engine loop, bounded
admission queue, per-request streaming iterators, deadlines/cancellation,
per-stage telemetry with a Prometheus dump, and the engine flight
recorder — a chrome trace of the serve plus the slow-token explainer —
dumped as an artifact on exit), plus the bare-engine loop for
comparison (ragged admission, per-request sampling params, speculative
decoding, int8 weight-only quantization).
"""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import LLMEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import AsyncLLMServer


def build_model():
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=256)
    model = LlamaForCausalLM(cfg).bfloat16()
    model.eval()
    # optional: int8 weight-only serving (measured ~2x decode throughput)
    # from paddle_tpu.nn.quant import quantize_linears_for_inference
    # quantize_linears_for_inference(model, weight_dtype="int8")
    return model


def main():
    model = build_model()
    rng = np.random.default_rng(0)

    # -- the production shape: AsyncLLMServer over the fused scheduler
    # (admission = slot assignment; prefill chunks interleave into the
    # decode batch under max_step_tokens instead of stalling it).
    # readout_stride=4: all-decode steps run up to 4 decode iterations
    # as ONE compiled on-device loop (in-graph early exit when every
    # slot finishes), so the host syncs once per 4 tokens — the
    # throughput tier. pipeline_depth=3: up to 3 dispatches in flight
    # ahead of the oldest readout (the fused engines' depth contract).
    eng = LLMEngine(model, max_batch=4, max_seq_len=128, chunk_size=32,
                    scheduler="fused", readout_stride=4)
    with AsyncLLMServer(eng, max_queue_size=16, pipeline_depth=3,
                        flight_recorder=True) as server:
        handles = [
            server.submit(rng.integers(1, 512, size=(n,)).astype(np.int32),
                          max_new_tokens=6, temperature=temp,
                          deadline_s=60.0,
                          # latency tier: one request pins stride 1 —
                          # every step it is resident in syncs per
                          # token (floor ITL, whole-batch cost)
                          readout_stride=1 if n == 7 else None)
            for n, temp in ((12, 0.0), (7, 0.8), (20, 0.0))]
        for h in handles:
            # per-request streaming iterator: tokens as they decode
            for tok in h:
                print(f"  [req {h.request_id}] token {tok}", flush=True)
            res = h.result()
            print(f"req {res.request_id} done ({res.finish_reason}): "
                  f"{res.token_ids}  ttft={res.ttft_s:.3f}s")
    print(server.telemetry.prometheus_text().splitlines()[0], "...")
    att = server.telemetry.snapshot()["attribution"]
    print(f"serve wall attributed: {att['attributed_share']:.0%} "
          f"across {list(att['stage_share'])}")
    # flight-recorder artifacts: a Perfetto-loadable timeline (one lane
    # per request + an engine-step lane) and the slow-token explainer
    rec = server.flight_recorder
    trace_path = os.environ.get("SERVE_TRACE_PATH",
                                "serve_llama_trace.json")
    rec.export_chrome_trace(trace_path)
    print(f"trace ({rec.snapshot()['steps_recorded']} engine steps) -> "
          f"{trace_path}  (open at ui.perfetto.dev)")
    for e in rec.explain_tail(0.9, top=3):
        print(f"  slow token: req {e['request_id']} gap "
              f"{e['gap_s'] * 1e3:.1f}ms @ step {e['step_id']} <- "
              f"{e['cause']}")

    # -- the bare engine loop (speculative decoding demo) --------------
    eng2 = LLMEngine(model, max_batch=4, max_seq_len=128, chunk_size=32,
                     speculative_k=4,          # prompt-lookup speculation
                     stream_callback=lambda rid, tok: print(
                         f"  [req {rid}] token {tok}", flush=True))
    for n, temp in ((12, 0.0), (7, 0.8)):
        eng2.add_request(rng.integers(1, 512, size=(n,)).astype(np.int32),
                         max_new_tokens=6, temperature=temp)
    while eng2.has_unfinished():
        for out in eng2.step():
            print(f"req {out.request_id} done ({out.finish_reason}): "
                  f"{out.token_ids}")
    print(f"engine stats: {eng2.stats}")


if __name__ == "__main__":
    main()
