"""Serve a llama-family model with the continuous-batching engine.

Run: python examples/serve_llama.py          # tiny demo model, mixed requests
Shows: ragged admission, streaming, per-request sampling params,
speculative decoding, int8 weight-only quantization.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import LLMEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=256)
    model = LlamaForCausalLM(cfg).bfloat16()
    model.eval()

    # optional: int8 weight-only serving (measured ~2x decode throughput)
    # from paddle_tpu.nn.quant import quantize_linears_for_inference
    # quantize_linears_for_inference(model, weight_dtype="int8")

    eng = LLMEngine(model, max_batch=4, max_seq_len=128, chunk_size=32,
                    speculative_k=4,          # prompt-lookup speculation
                    stream_callback=lambda rid, tok: print(
                        f"  [req {rid}] token {tok}", flush=True))

    rng = np.random.default_rng(0)
    for n, temp in ((12, 0.0), (7, 0.8), (20, 0.0)):
        eng.add_request(rng.integers(1, 512, size=(n,)).astype(np.int32),
                        max_new_tokens=6, temperature=temp)
    while eng.has_unfinished():
        for out in eng.step():
            print(f"req {out.request_id} done ({out.finish_reason}): "
                  f"{out.token_ids}")
    print(f"engine stats: {eng.stats}")


if __name__ == "__main__":
    main()
