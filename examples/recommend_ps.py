"""Recommendation-style training with the native parameter server: a huge
sparse embedding lives on PS table nodes, the dense tower trains on device.

Run: python examples/recommend_ps.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.incubate.distributed import ps


def main():
    servers = [ps.NativePSServer() for _ in range(2)]
    client = ps.NativePSClient([s.endpoint for s in servers])
    emb = ps.DistributedEmbedding(client, "user_emb", 16,
                                  optimizer="adagrad", lr=0.1, seed=0)
    tower = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                                 paddle.nn.Linear(32, 1))
    dense_opt = opt.AdamW(learning_rate=1e-3, parameters=tower.parameters())

    rng = np.random.default_rng(0)
    V = 100_000  # ids far beyond device-memory embedding sizes
    targets = {}
    for step in range(30):
        ids_np = rng.integers(0, V, size=(64,))
        y_np = np.array([targets.setdefault(i, rng.standard_normal())
                         for i in ids_np], np.float32)[:, None]
        out = tower(emb(paddle.to_tensor(ids_np)))
        loss = ((out - paddle.to_tensor(y_np)) ** 2).mean()
        loss.backward()
        emb.push_step()          # sparse rows -> PS (adagrad on the server)
        dense_opt.step()
        dense_opt.clear_grad()
        if step % 10 == 0 or step == 29:
            st = client.stats("user_emb")
            print(f"step {step}: loss {float(loss.numpy()):.4f} "
                  f"(PS rows={st['rows']})", flush=True)
    client.close()
    for s in servers:
        s.stop()


if __name__ == "__main__":
    main()
