"""Disaggregated prefill/decode serving: cross-replica KV shipping.

Run: python examples/serve_disagg.py     # tiny demo model, CPU-friendly
Shows: a two-replica fleet where replica 0 ONLY prefills and replica 1
ONLY decodes. A generate request is submitted to the prefill replica as
a one-token leg with KV export staged at finish; the router ships the
staged entry over the transport (in-process loopback here — the PTKV
wire format is bytes-on-wire, so an RDMA/ICI transport is one class),
the decode replica imports it into its swap store, and the request
resumes there with the KV tier's one-token stitch: ONE prefill token
per migration, zero re-prefill, token-exact vs mixed placement (greedy
and seeded-sampled). Any ship failure falls back to plain re-prefill
with unchanged tokens. Also printed: ship counters, the
migration-latency histogram with its per-phase split, the fleet
explain_tail verdicts, and the per-replica kv_tier view. On exit the
router dumps its postmortem artifacts — the STITCHED cross-replica
Perfetto trace (one connected flow-linked chain per migrated request;
open at ui.perfetto.dev) and the fleet debug-bundle directory readable
by ``python -m paddle_tpu.profiler.bundle`` — under
``SERVE_DISAGG_OUT`` (default docs/artifacts/).
"""
import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import LLMEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.profiler import FlightRecorder
from paddle_tpu.serving import AsyncLLMServer, ReplicaRouter


def build_engine():
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                      intermediate_size=256, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4,
                      max_position_embeddings=256)
    model = LlamaForCausalLM(cfg).bfloat16()
    model.eval()
    # the ship path rides the KV tier's gather/scatter: paged + fused
    # are required on both ends (import_kv validates the geometry)
    return LLMEngine(model, max_batch=4, max_seq_len=128, chunk_size=32,
                     cache_impl="paged", block_size=16, scheduler="fused",
                     sampling_seed=7)


def main():
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 512, size=(n,)).astype(np.int32)
               for n in (48, 33, 61)]

    # reference: the same prompts on ONE mixed engine — disaggregation
    # must not change a single token
    ref = [r.token_ids for r in
           build_engine().generate(prompts, max_new_tokens=12)]

    replicas = [AsyncLLMServer(build_engine(), replica=i,
                               flight_recorder=FlightRecorder())
                for i in range(2)]
    with ReplicaRouter(replicas,
                       roles={"prefill": [0], "decode": [1]}) as router:
        handles = [router.submit(p, max_new_tokens=12) for p in prompts]
        for h, want in zip(handles, ref):
            res = h.result(timeout=300)
            ok = "token-exact" if res.token_ids == want else "MISMATCH"
            tc = res.trace_ctx
            print(f"req {res.request_id}: {res.token_ids[:6]}... "
                  f"({res.finish_reason}, {ok})  trace {tc.trace_id} "
                  f"hop {tc.hop} via {tc.via}")

        snap = router.snapshot()
        print(f"\nshipped {router.stats['kv_shipped']} requests "
              f"({snap['transport']['ship_bytes']} wire bytes), "
              f"{router.stats['kv_ship_fallback']} fallbacks")
        print("migration latency:", snap["migration_latency"])
        for phase, h in snap["migration_phases"].items():
            print(f"  kv_ship:{phase}: {h}")
        for e in router.explain_tail(0.0, top=3):
            print(f"  tail: req {e['request_id']} [{e.get('trace_id')}] "
                  f"gap {e['gap_s'] * 1e3:.1f}ms <- {e['cause']}")
        dec = snap["replicas"][1]
        print(f"decode replica prefill_tokens="
              f"{replicas[1].engine.stats['prefill_tokens']} "
              f"(= one stitch token per migration), kv_tier={dec['kv_tier']}")

        # postmortem artifacts: the stitched cross-replica trace (flow
        # events join the prefill and decode legs of each request into
        # one chain) + a fleet debug-bundle directory
        out = os.environ.get("SERVE_DISAGG_OUT",
                             os.path.join(os.path.dirname(__file__),
                                          "..", "docs", "artifacts"))
        trace_path = os.path.join(out, "serve_disagg_trace.json")
        router.export_merged_trace(trace_path)
        ev = json.load(open(trace_path))["traceEvents"]
        flows = sum(1 for e in ev if e.get("ph") == "s")
        print(f"\nstitched trace: {len(ev)} events, {flows} cross-replica "
              f"flows -> {trace_path}  (open at ui.perfetto.dev)")
        paths = router.dump_debug_bundle(
            os.path.join(out, "serve_disagg_bundle"))
        print(f"fleet debug bundle -> {os.path.dirname(paths['router'])}  "
              f"(read: python -m paddle_tpu.profiler.bundle "
              f"{paths['replicas'][0]})")
    for line in replicas[1].telemetry.prometheus_text().splitlines():
        if "kv_ship" in line and not line.startswith("#"):
            print(line)


if __name__ == "__main__":
    main()
