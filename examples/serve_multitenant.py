"""Multi-tenant serving: batched multi-LoRA + prefill-only embeddings
through ONE fused engine.

Run: python examples/serve_multitenant.py     # tiny demo model, CPU-ok
Shows: an AdapterStore with two registered LoRA adapters (tenants 1 and
2) served CONCURRENTLY with base-model traffic (tenant 0) and
prefill-only embedding requests, all through one AsyncLLMServer over one
fused paged LLMEngine — every tenant's rows gather its own low-rank
delta inside the same compiled mixed step, embedding prompts ride the
same token-budget walk as generation chunks, and the prefix cache keys
KV blocks per tenant. The telemetry snapshot (adapter cache
hits/misses/swaps, occupancy gauge, per-tenant token counters, embed
request count) lands in docs/artifacts/multitenant_telemetry.json.

The SLO sensor layer rides the same server: a metrics store turns the
gauges into time series, per-tenant TTFT histograms split the traffic,
a per-tenant `ttft_p99` SLO evaluates with multi-window burn-rate
alerting, and the live pathology detectors watch the flight recorder's
StepRecords — `server.slo_report()` (JSON + human text) lands in
docs/artifacts/multitenant_slo_report.json.
"""
import json
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import LLMEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.profiler import SLO
from paddle_tpu.serving import (AdapterStore, AsyncLLMServer,
                                random_lora_weights)

CFG = LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=4, max_position_embeddings=256)


def build_model():
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    model.eval()
    return model


def main():
    rng = np.random.default_rng(0)
    model = build_model()

    # -- two tenants: small random LoRA factors over q/k/v/o + MLP
    store = AdapterStore(CFG, rank=8)
    tenant_a = store.register(
        random_lora_weights(CFG, rank=8, seed=1, scale=0.05), alpha=2.0)
    tenant_b = store.register(
        random_lora_weights(CFG, rank=4, seed=2, scale=0.05), alpha=1.0)
    print(f"registered adapters: {store.ids()} (rank pad to {store.rank})")

    engine = LLMEngine(model, max_batch=4, max_seq_len=128, chunk_size=32,
                       cache_impl="paged", block_size=16,
                       scheduler="fused", enable_prefix_cache=True,
                       adapter_store=store, adapter_cache_slots=2)
    # the SLO sensor layer: metrics store (gauges/counters as time
    # series + per-tenant latency samples), one per-tenant latency
    # objective, and — because a flight recorder is attached too — the
    # default live pathology detectors
    server = AsyncLLMServer(
        engine, max_queue_size=32, flight_recorder=True,
        metrics_store=True, metrics_interval_s=0.02,
        # target generous enough to absorb the demo's cold-compile
        # TTFT — the llama_serve_slo bench CALIBRATES its target from a
        # warmup phase instead, which is the production-shaped move
        slos=[SLO("tenant_a_ttft", "ttft_p99", tenant=tenant_a,
                  target_s=60.0, window_s=30.0)])
    server.start()

    system_prompt = rng.integers(1, 512, size=(32,)).astype(np.int32)

    def prompt():
        tail = rng.integers(1, 512,
                            size=(int(rng.integers(4, 12)),)).astype(np.int32)
        return np.concatenate([system_prompt, tail])

    # -- mixed multi-tenant submits: base + 2 adapters + embeddings,
    # all batched through the same fused token-budget walk
    handles = []
    for i in range(6):
        aid = (0, tenant_a, tenant_b)[i % 3]
        handles.append((aid, server.submit(prompt(), max_new_tokens=16,
                                           adapter_id=aid)))
    embeds = [server.submit_embed(prompt(), adapter_id=aid)
              for aid in (0, tenant_a, tenant_b)]

    for aid, h in handles:
        out = h.result(timeout=600)
        print(f"tenant {aid}: rid={out.request_id} "
              f"finish={out.finish_reason} tokens={out.token_ids[:8]}...")
    for h in embeds:
        out = h.result(timeout=600)
        vec = out.embedding
        print(f"embed rid={out.request_id}: shape={vec.shape} "
              f"norm={float(np.linalg.norm(vec)):.3f}")

    snap = server.telemetry.snapshot()
    slo_report = server.slo_report()
    server.stop()

    interesting = {k: snap["counters"][k] for k in
                   ("adapter_cache_hits", "adapter_cache_misses",
                    "adapter_swaps", "embed_requests",
                    "prefix_hit_tokens", "tokens_emitted")}
    print("adapter/embed counters:", interesting)
    print("tenant tokens:", snap["tenant_tokens"])
    print("adapter cache occupancy:",
          snap["gauges"]["adapter_cache_occupancy"])

    art_dir = os.path.join(os.path.dirname(__file__), "..", "docs",
                           "artifacts")
    os.makedirs(art_dir, exist_ok=True)
    path = os.path.abspath(
        os.path.join(art_dir, "multitenant_telemetry.json"))
    with open(path, "w") as f:
        json.dump(snap, f, indent=1)
    print(f"telemetry snapshot -> {path}")

    print("\nSLO report:")
    print(slo_report["text"])
    print("per-tenant ttft p99 (ms):",
          {t: round(fams["ttft"]["p99_s"] * 1e3, 1)
           for t, fams in slo_report["tenant_latency"].items()})
    slo_path = os.path.abspath(
        os.path.join(art_dir, "multitenant_slo_report.json"))
    with open(slo_path, "w") as f:
        json.dump(slo_report, f, indent=1)
    print(f"slo report -> {slo_path}")


if __name__ == "__main__":
    main()
